"""Deterministic, seeded fault injection for training loops.

Every recovery path in the supervisor must be exercised by test, not by
luck: ``ChaosMonkey`` wraps a train step and fires faults at
deterministically chosen steps, so a CI run with ``seed=7`` reproduces
the exact failure sequence of any previous run with ``seed=7``.

Faults
------

``nan``      the step returns a non-finite loss (poisoned batch / bf16
             overflow analog); the real step is NOT run, matching a loss
             that was computed but useless
``stall``    the step blocks for ``stall_s`` then raises
             :class:`StallInjected` (the wedged-TPU-tunnel analog seen in
             BENCH_r02–r05); nothing mutates, so a retry is safe
``error``    the step raises :class:`ChaosError` (transient RPC failure)
``kill``     SIGKILL to the current process — no atexit, no flushing;
             only a durable checkpoint survives this
``corrupt``  the newest committed checkpoint gets one shard truncated
             (restore must detect the bad checksum and fall back)

Serving faults (consumed by ``serving.resilience.EngineSupervisor`` via
:meth:`ChaosMonkey.take` — the supervisor, not the monkey, performs the
injection because each fault manipulates live engine state):

``decode-stall``   the fused decode step wedges past its deadline then
                   fails (TPU-tunnel analog on the serving path)
``decode-raise``   the decode step raises (transient device/RPC error)
``kv-corrupt``     an active KV slot's attendable lines are poisoned in
                   place (:func:`corrupt_kv`); the supervisor's probe
                   must catch it before the next decode consumes it
``abandon``        a client abandons an in-flight request mid-stream

Schedules are explicit (``at={step: fault}``) or drawn from a seeded RNG
(``p`` per-step probability over ``faults``); both are pure functions of
the constructor arguments.
"""
from __future__ import annotations

import os
import signal
import time

import numpy as np

FAULTS = ("nan", "stall", "error", "kill", "corrupt")
SERVING_FAULTS = ("decode-stall", "decode-raise", "kv-corrupt", "abandon")
#: Consumed by ``serving.fleet.ReplicaFleet`` (one fault per fleet step,
#: injected into a deterministically chosen replica): ``replica-kill``
#: condemns a replica's engine outright (process-death analog; requests
#: migrate to peers), ``route-flap`` randomizes the next few routing
#: decisions (placement must not change tokens), and the decode-* /
#: kv-corrupt serving faults target one replica's engine.
FLEET_FAULTS = ("replica-kill", "route-flap", "decode-stall",
                "decode-raise", "kv-corrupt")


class ChaosError(RuntimeError):
    """Injected transient step failure (RPC-error analog)."""


class StallInjected(TimeoutError):
    """Injected wedged step: blocked past the deadline, then failed."""


class ChaosMonkey:
    """Wrap a train step so faults fire at deterministic steps.

    ``at`` maps 0-based step invocation index -> fault name for an
    explicit plan; alternatively ``p`` > 0 draws a schedule from
    ``numpy.random.default_rng(seed)`` over ``faults`` for ``horizon``
    steps. ``wrap(step_fn)`` returns the chaotic step; the monkey counts
    invocations, so the Nth call fires the fault planned for step N
    (a retried step advances the count — retries meet fresh weather).
    """

    def __init__(self, seed: int = 0, *, at=None, p: float = 0.0,
                 faults=("nan", "stall", "error"), horizon: int = 1024,
                 stall_s: float = 0.25, manager=None):
        self.seed = int(seed)
        self.stall_s = float(stall_s)
        self.manager = manager
        self.calls = 0
        self.fired = []                 # [(step, fault)]
        # observability: every fired fault gets a trace id (minted even
        # with the tracer off) so chaos verdicts/ledgers link a fault to
        # its spans; last_trace_id is the most recent fault's
        self.trace_ids = {}             # step -> trace id
        self.last_trace_id = None
        known = FAULTS + SERVING_FAULTS + FLEET_FAULTS
        for f in tuple(dict(at or {}).values()) + tuple(faults):
            if f not in known:
                raise ValueError(f"unknown fault {f!r} (one of {known})")
        self.plan = {int(k): v for k, v in (at or {}).items()}
        if p > 0.0:
            rng = np.random.default_rng(self.seed)
            for step in range(int(horizon)):
                if step in self.plan:
                    continue
                if rng.random() < p:
                    self.plan[step] = str(rng.choice(list(faults)))

    def schedule(self, n_steps: int):
        """The fault plan restricted to the first ``n_steps`` steps."""
        return {s: f for s, f in sorted(self.plan.items()) if s < n_steps}

    def take(self):
        """Consume one supervised step's planned fault (or None) without
        executing it — the serving EngineSupervisor drives injection
        itself because serving faults manipulate live engine state.
        Counts an invocation exactly like :meth:`wrap`'s chaotic step,
        so the Nth supervised step meets the fault planned for step N."""
        step = self.calls
        self.calls += 1
        fault = self.plan.get(step)
        if fault is not None:
            self.fired.append((step, fault))
            self._mark_fired(step, fault)
        return fault

    def _mark_fired(self, step, fault):
        from ..observability import tracing
        tid = tracing.new_trace_id()
        self.trace_ids[step] = tid
        self.last_trace_id = tid
        tracing.instant(f"chaos.{fault}", cat="chaos", trace_id=tid,
                        step=step, seed=self.seed)

    def wrap(self, step_fn):
        def chaotic_step(*args, **kwargs):
            step = self.calls
            self.calls += 1
            fault = self.plan.get(step)
            if fault is not None:
                self.fired.append((step, fault))
                self._mark_fired(step, fault)
                return self._fire(fault, step_fn, args, kwargs)
            return step_fn(*args, **kwargs)

        chaotic_step.chaos = self
        return chaotic_step

    def _fire(self, fault, step_fn, args, kwargs):
        if fault == "nan":
            return float("nan")
        if fault == "stall":
            time.sleep(self.stall_s)
            raise StallInjected(
                f"chaos: step wedged for {self.stall_s}s (seed={self.seed})")
        if fault == "error":
            raise ChaosError(f"chaos: transient step failure "
                             f"(seed={self.seed})")
        if fault == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
            raise RuntimeError("unreachable: SIGKILL did not fire")
        if fault == "corrupt":
            if self.manager is None:
                raise ValueError(
                    "chaos fault 'corrupt' needs ChaosMonkey(manager=...)")
            corrupt_latest(self.manager, seed=self.seed)
            return step_fn(*args, **kwargs)
        raise ValueError(f"unknown fault {fault!r}")


# ---------------------------------------------------------------------------
# checkpoint corruption helpers (used by chaos 'corrupt' and by tests)
# ---------------------------------------------------------------------------

def corrupt_checkpoint(path, seed: int = 0, mode: str = "truncate"):
    """Damage a committed checkpoint dir in place.

    ``truncate`` halves a deterministically chosen data file; ``flip``
    xors one byte; ``uncommit`` removes the COMMIT marker (simulating a
    kill after rename of a pre-manifest writer). Returns the damaged
    file path (or the marker path for ``uncommit``).
    """
    path = os.path.abspath(path)
    if mode == "uncommit":
        marker = os.path.join(path, "COMMIT")
        os.remove(marker)
        return marker
    files = []
    for root, _dirs, names in os.walk(path):
        for name in names:
            if name == "COMMIT":
                continue
            full = os.path.join(root, name)
            if os.path.getsize(full) > 0:
                files.append(full)
    if not files:
        raise FileNotFoundError(f"no data files to corrupt under {path}")
    files.sort()
    rng = np.random.default_rng(seed)
    victim = files[int(rng.integers(len(files)))]
    size = os.path.getsize(victim)
    if mode == "truncate":
        with open(victim, "rb+") as fh:
            fh.truncate(max(size // 2, 1))
    elif mode == "flip":
        off = int(rng.integers(size))
        with open(victim, "rb+") as fh:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return victim


def corrupt_latest(manager, seed: int = 0, mode: str = "truncate"):
    """Corrupt the newest committed checkpoint of a CheckpointManager."""
    manager.wait()
    step = manager.latest_step()
    if step is None:
        raise FileNotFoundError(
            f"no checkpoints under {manager.directory}")
    return corrupt_checkpoint(
        os.path.join(manager.directory, f"ckpt-{step}"), seed=seed,
        mode=mode)


def corrupt_kv(engine, seed: int = 0, value: float = float("nan")):
    """Serving-side corruption analog (chaos fault ``kv-corrupt``):
    poison deterministically chosen live KV state in place. The
    EngineSupervisor's finiteness probe must catch this BEFORE the next
    decode step consumes it; rebuild-and-replay then *heals* the state
    by recomputing KV from each request's own prompt + emitted-token
    history.

    Slot layout: one active slot's attendable lines are poisoned
    (returns the slot index). Paged layout: one live BLOCK is poisoned —
    preferring a SHARED prefix block (refcount > 1) when one exists, the
    nastiest case: every sharer reads it, so the verdict must show ALL
    of them healed by replay (returns the block id)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    cache = engine.cache
    if hasattr(cache, "live_blocks"):              # paged pool
        shared = cache.shared_live_blocks()
        cand = shared if shared else cache.live_blocks()
        if not cand:
            raise ValueError("no live blocks to corrupt")
        block = int(cand[int(rng.integers(len(cand)))])
        kc = np.asarray(cache.kc).copy()
        kc[:, block] = value
        old_sharding = getattr(cache.kc, "sharding", None)
        if old_sharding is not None and hasattr(old_sharding, "mesh"):
            # tensor-parallel pool: keep the NamedSharding so the poisoned
            # array still matches the SPMD program's operand signature
            import jax
            cache.kc = jax.device_put(kc, old_sharding)
        else:
            cache.kc = jnp.asarray(kc)
        return block
    active = np.nonzero(cache.active)[0]
    if active.size == 0:
        raise ValueError("no active slots to corrupt")
    slot = int(active[int(rng.integers(active.size))])
    lines = max(int(cache.cur_pos[slot]), 1)
    kc = np.asarray(cache.kc).copy()
    kc[:, slot, :lines] = value
    cache.kc = jnp.asarray(kc)
    return slot
