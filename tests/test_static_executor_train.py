"""Whole-program jitted Executor for TRAINING programs.

Reference: fluid/executor.py — the 1.x idiom is `opt.minimize(loss)` once
at build, then `exe.run(feed, fetch_list=[loss])` in a loop; the C++
executor runs the whole ProgramDesc (forward + grad ops + optimizer ops)
fused. The TPU-native analog (static/program.py::_build_replay_plan)
compiles that loop body into ONE jax.jit program per (program, feed
signature, fetch set): jax.grad re-derives the backward inside the trace,
the optimizer's pure update_param fuses the step, While/Switch lower to
lax control flow, and parameter/moment buffers are DONATED so the update
is copy-free.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import nn, static
from paddle_tpu import optimizer as optim
from paddle_tpu.fluid import layers


def _make_regression(n=64, d=4, seed=1):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, 1)).astype(np.float32)
    return xs, (xs @ w).astype(np.float32)


def _build_train_program(opt_factory, depth=2, width=8):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [None, 4], 'float32')
        yt = static.data('y', [None, 1], 'float32')
        h = x
        params = []
        for _ in range(depth):
            layer = nn.Linear(int(h.shape[-1]), width)
            params += layer.parameters()
            h = paddle.nn.functional.relu(layer(h))
        head = nn.Linear(width, 1)
        params += head.parameters()
        loss = ((head(h) - yt) ** 2).mean()
        opt = opt_factory(params)
        opt.minimize(loss)
    return main, loss


def _run_steps(main, loss, xs, ys, steps):
    exe = static.Executor()
    out = []
    for _ in range(steps):
        lv, = exe.run(main, feed={'x': xs, 'y': ys}, fetch_list=[loss])
        out.append(float(lv))
    return out


def _the_plan(prog):
    plans = [p for p in prog._jit_cache.values() if p is not None]
    assert plans, "train program did not take the compiled path"
    return plans[0]


class TestCompiledTrainLoop:
    def test_minimize_loop_matches_eager(self):
        """(a) the classic fluid loop: minimize + repeated exe.run.
        The first fetched loss (pure forward, fresh params) must match
        the eager op-by-op replay bitwise; post-update losses may drift
        by fusion ULPs only (tools/bench_static_executor.py --train
        asserts full bitwise equality on its pinned config)."""
        xs, ys = _make_regression()

        def sgd(params):
            return fluid.optimizer.SGDOptimizer(
                learning_rate=0.1, parameter_list=params)

        main, loss = _build_train_program(sgd)
        jit_losses = _run_steps(main, loss, xs, ys, 5)
        os.environ['PADDLE_TPU_STATIC_JIT'] = '0'
        try:
            main2, loss2 = _build_train_program(sgd)
            eager_losses = _run_steps(main2, loss2, xs, ys, 5)
        finally:
            del os.environ['PADDLE_TPU_STATIC_JIT']
        assert jit_losses[0] == eager_losses[0], \
            (jit_losses[0], eager_losses[0])
        np.testing.assert_allclose(jit_losses, eager_losses,
                                   rtol=1e-5, atol=1e-7)
        assert jit_losses[-1] < jit_losses[0]

    def test_compiled_path_taken_and_cached(self):
        """(b) one build, then cache hits: the plan's call counter moves
        once per exe.run and no host entries leak into the plan."""
        xs, ys = _make_regression()
        main, loss = _build_train_program(
            lambda ps: optim.SGD(learning_rate=0.1, parameters=ps))
        _run_steps(main, loss, xs, ys, 4)
        plan = _the_plan(main)
        # first sighting runs eager (compile defers until the key
        # repeats), every later step goes through the plan
        assert plan.calls == 3
        assert plan.n_host == 0
        assert len(plan.segments) == 1  # whole program, single callable
        assert len(main._jit_cache) == 1  # one key: no rebuild per step

    def test_adam_moments_thread_through_compiled_state(self):
        """Adam's moments live in the donated state, not re-read from
        zero: the compiled loop must converge like eager (values drift
        by float-fusion ULPs, trajectories must stay close)."""
        xs, ys = _make_regression()

        def adam(params):
            return optim.Adam(learning_rate=0.05, parameters=params)

        main, loss = _build_train_program(adam)
        jit_losses = _run_steps(main, loss, xs, ys, 10)
        os.environ['PADDLE_TPU_STATIC_JIT'] = '0'
        try:
            main2, loss2 = _build_train_program(adam)
            eager_losses = _run_steps(main2, loss2, xs, ys, 10)
        finally:
            del os.environ['PADDLE_TPU_STATIC_JIT']
        np.testing.assert_allclose(jit_losses, eager_losses,
                                   rtol=1e-4, atol=1e-6)
        plan = _the_plan(main)
        seg = plan.segments[0]
        # params + moment1/moment2/beta1_pow/beta2_pow per param
        kinds = [s[0] for s in seg.state_specs]
        assert kinds.count("opt") == 4 * kinds.count("param")

    def test_while_training_program_compiles_single_callable(self):
        """(c) a Program containing While AND minimize executes via one
        jitted callable — no per-op eager dispatch."""
        paddle.seed(0)
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [None, 4], 'float32')
            yt = static.data('y', [None, 1], 'float32')
            layer = nn.Linear(4, 1)
            base = ((layer(x) - yt) ** 2).mean()
            # While computes a loop-carried scale (grad-free host-style
            # counter loop — the 1.x warmup/readjust idiom)
            lim = layers.fill_constant([1], 'float32', 3.0)
            i = layers.fill_constant([1], 'float32', 0.0)
            cond = layers.less_than(i, lim)
            w = layers.While(cond)
            with w.block():
                layers.increment(i, value=1.0)
                layers.less_than(i, lim, cond=cond)
            scale = layers.elementwise_add(
                i, layers.fill_constant([1], 'float32', 0.0))
            scale.stop_gradient = True
            loss = base * scale
            opt = optim.SGD(learning_rate=0.02,
                            parameters=layer.parameters())
            opt.minimize(loss)
        xs, ys = _make_regression(n=16)
        jit_losses = _run_steps(main, loss, xs, ys, 3)
        plan = _the_plan(main)
        assert plan.calls == 2 and plan.n_host == 0 \
            and len(plan.segments) == 1
        kinds = [e[0] for e in main._ops]
        assert "while" in kinds and "minimize" in kinds
        os.environ['PADDLE_TPU_STATIC_JIT'] = '0'
        try:
            eager_losses = _run_steps(main, loss, xs, ys, 3)
        finally:
            del os.environ['PADDLE_TPU_STATIC_JIT']
        # the compiled runs already advanced the params; eager continues
        # the SAME trajectory, so losses keep decreasing smoothly
        assert eager_losses[0] < jit_losses[-1]

    def test_append_backward_grads_compiled(self):
        """append_backward programs compile too: fetched grad holders
        come from jax.grad inside the trace and match the closed form."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [None, 3], 'float32')
            w = static.create_parameter([3, 1], 'float32')
            w.stop_gradient = False
            loss = x.matmul(w).sum()
            grads = static.append_backward(loss, parameter_list=[w])
        exe = static.Executor()
        feed = np.ones((5, 3), dtype=np.float32)
        for _ in range(2):
            _, g = exe.run(main, feed={'x': feed},
                           fetch_list=[loss, grads[0][1]])
        np.testing.assert_allclose(g, 5 * np.ones((3, 1)), atol=1e-6)
        plan = _the_plan(main)
        assert plan.calls == 1 and plan.n_host == 0

    def test_param_and_moment_buffers_donated(self):
        """Parameter/moment buffers are donated into the compiled train
        step: the lowering carries input-output aliases AND the previous
        param buffer is actually invalidated after a step (no O(params)
        copy kept alive)."""
        xs, ys = _make_regression()
        main, loss = _build_train_program(
            lambda ps: optim.Adam(learning_rate=0.05, parameters=ps))
        _run_steps(main, loss, xs, ys, 2)  # eager step, then build+run
        plan = _the_plan(main)
        seg = plan.segments[0]
        assert seg.donated
        n_state = len(seg.state_specs)
        assert n_state > 0 and seg.alias_count >= n_state
        # live-buffer proof: the pre-step param buffer dies on donation
        param = next(s[1] for s in seg.state_specs if s[0] == "param")
        before = param._data
        _run_steps(main, loss, xs, ys, 1)
        assert param._data is not before
        assert before.is_deleted(), \
            "old param buffer still alive — donation did not happen"

    def test_host_entry_keeps_per_op_eager_fallback(self):
        """py_func host IO drops ONLY that entry to eager — the
        surrounding ops still run compiled (segmented plan)."""
        seen = []
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [None, 2], 'float32')
            h = x * 2.0
            out_holder = paddle.Tensor(np.zeros((1,), np.float32))
            static.py_func(lambda t: (seen.append(1),
                                      np.asarray(t._data).sum())[1],
                           h, out_holder)
            y = h + 1.0
        exe = static.Executor()
        for _ in range(3):
            got, = exe.run(main, feed={'x': np.ones((2, 2), np.float32)},
                           fetch_list=[y])
        np.testing.assert_allclose(got, 3 * np.ones((2, 2)))
        plan = _the_plan(main)
        assert plan.n_host == 1 and len(plan.segments) == 2
        assert plan.calls == 2  # step 1 eager, steps 2-3 via the plan
        assert len(seen) == 3  # host thunk really ran every step


class TestSatelliteRegressions:
    def test_fetch_cache_key_uses_stable_tokens_not_id(self):
        """ADVICE #5: fetch Tensors key by a monotonic per-Tensor token;
        id() reuse after GC can never resurrect a stale cache verdict."""
        from paddle_tpu.static.program import _stable_token
        a = paddle.Tensor(np.zeros((1,), np.float32))
        tok_a = _stable_token(a)
        assert _stable_token(a) == tok_a  # stable across calls
        b = paddle.Tensor(np.zeros((1,), np.float32))
        assert _stable_token(b) != tok_a
        del a
        import gc
        gc.collect()
        c = paddle.Tensor(np.zeros((1,), np.float32))
        assert _stable_token(c) not in (tok_a, _stable_token(b))
        # and the cache key embeds the token, not id()
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [None, 2], 'float32')
            y = x * 2.0
        exe = static.Executor()
        exe.run(main, feed={'x': np.ones((1, 2), np.float32)},
                fetch_list=[y])
        (key,) = main._jit_cache.keys()
        assert key[2] == (("#t", _stable_token(y)),)

    def test_kl_divergence_categorical_keepdims_shape(self):
        """ADVICE #1: module-level kl_divergence delegates to the method
        so Categorical keeps the reference [..., 1] contract."""
        from paddle_tpu.distribution import Categorical, kl_divergence
        logits_p = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32))
        logits_q = paddle.to_tensor(
            np.random.default_rng(1).normal(size=(5, 3)).astype(np.float32))
        p, q = Categorical(logits_p), Categorical(logits_q)
        out = kl_divergence(p, q)
        assert out.shape == [5, 1]
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(p.kl_divergence(q)._data))

    def test_asp_masked_step_skips_when_step_owns_no_params(self):
        """ADVICE #2: a step exposing no params must NOT widen the mask
        reapply to every pruned model in the process."""
        from paddle_tpu.distributed.fleet import _ASPMaskedStep
        from paddle_tpu.static import sparsity

        calls = []
        orig = sparsity._reapply_masks
        sparsity._reapply_masks = lambda only_ids=None: calls.append(only_ids)
        try:
            class _Step:
                _params = {}

                def __call__(self):
                    return "ok"
            assert _ASPMaskedStep(_Step())() == "ok"
            assert calls == [], "empty step must skip the reapply entirely"

            class _Owner:
                def __call__(self):
                    return "ok"
            p = paddle.Parameter(np.ones((2, 2), np.float32))
            owner = _Owner()
            owner._params = {"w": p}
            _ASPMaskedStep(owner)()
            assert calls == [{id(p)}]  # scoped, never None
        finally:
            sparsity._reapply_masks = orig

    def test_global_scatter_gather_validate_counts_eager(self):
        """ADVICE #3: world_size-1 eager path raises on mismatched
        local/global counts instead of silently slicing wrong rows."""
        from paddle_tpu.distributed.utils import (global_gather,
                                                  global_scatter)
        x = paddle.to_tensor(np.ones((4, 2), np.float32))
        lc = paddle.to_tensor(np.asarray([2, 2], np.int64))
        gc_bad = paddle.to_tensor(np.asarray([1, 2], np.int64))
        gc_ok = paddle.to_tensor(np.asarray([3, 1], np.int64))
        with pytest.raises(ValueError, match="local_count.sum"):
            global_scatter(x, lc, gc_bad)
        with pytest.raises(ValueError, match="local_count.sum"):
            global_gather(x, lc, gc_bad)
        out = global_scatter(x, lc, gc_ok)
        assert out.shape == [4, 2]
