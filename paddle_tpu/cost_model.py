"""Cost model.

Reference: python/paddle/cost_model/cost_model.py — estimates per-op /
whole-program cost by profiling the executor. TPU-native design: XLA
already computes an analytical cost model for every compiled executable,
so this asks the compiler (``jax.jit(...).lower().compile()
.cost_analysis()``) instead of timing kernels, and falls back to wall-time
profiling when asked.

Since ISSUE 14 this module is also the kernel autotuner's OFFLINE ranker
(``paddle_tpu.tuner`` with no accelerator up): a candidate tile config's
score is the shape's config-independent ``cost_analysis()`` base (when
available) times three deterministic penalty terms —

* **tile alignment** — tile dims that aren't multiples of their
  hardware alignment (sublane x lane minima per dtype) pay the padding
  waste they'd cause on the MXU/VPU;
* **VMEM footprint** — a config whose resident blocks exceed the
  ~16 MB/core VMEM budget would spill (or refuse to compile) on real
  hardware and is pushed to the back of the ranking;
* **grid overhead** — a mild per-grid-step term so degenerate
  tiny-tile configs don't tie with sane ones.

Scores are pure functions of (features, base): the same space ranks
identically in every process, which is what makes the offline winner
deterministic and cacheable.
"""
from __future__ import annotations

import time

#: per-core VMEM budget the penalty model assumes (v4/v5e class)
VMEM_LIMIT_BYTES = 16 * 1024 * 1024

#: min (sublane, lane) tile per dtype itemsize — the pallas guide's
#: tiling table; itemsizes not listed fall back to fp32's (8, 128)
_MIN_TILE_BY_ITEMSIZE = {4: (8, 128), 2: (16, 128), 1: (32, 128)}


def min_tile(itemsize: int):
    """(sublane, lane) hardware tile minimum for an operand itemsize."""
    return _MIN_TILE_BY_ITEMSIZE.get(int(itemsize), (8, 128))


def _unwrap(x):
    """Tensor-like wrappers expose the device array as ``._data``."""
    return getattr(x, "_data", x)


class CostModel:
    def static_cost_data(self):
        """Reference returns op-cost table data used by auto-parallel; the
        XLA path has no static per-op table — costs come per-program from
        cost_analysis()."""
        return {}

    def profile_measure(self, fn, args=(), kwargs=None, device="tpu",
                        fetch_cost_list=("time",), warmup=1, iters=10,
                        batches=1):
        """Measure a python callable's wall time (compiled path included).

        Blocks on the WHOLE output pytree (tuple/dict/Tensor outputs all
        synchronize — timing only the first leaf under-reports on
        multi-output programs). With ``batches > 1`` the call runs
        ``batches`` independent batches of ``iters`` and also reports
        ``time_min`` — the min-of-batches mean, the noise-robust figure
        the tuner and the observability overhead claims rank on."""
        import jax
        kwargs = kwargs or {}

        def sync(out):
            jax.block_until_ready(
                jax.tree_util.tree_map(_unwrap, out))

        for _ in range(warmup):
            out = fn(*args, **kwargs)
        if warmup:
            sync(out)
        per_batch = []
        for _ in range(max(1, int(batches))):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args, **kwargs)
            sync(out)
            per_batch.append((time.perf_counter() - t0) / iters)
        return {"time": sum(per_batch) / len(per_batch),
                "time_min": min(per_batch),
                "batches": per_batch}

    def xla_cost(self, fn, *example_args):
        """Analytical cost of a jittable raw-array function: flops, bytes
        accessed, and optimal seconds estimate from XLA."""
        import jax
        compiled = jax.jit(fn).lower(*example_args).compile()
        analyses = compiled.cost_analysis()
        ca = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
        ca = ca or {}
        return {
            "flops": float(ca.get("flops", -1.0)),
            "bytes_accessed": float(ca.get("bytes accessed", -1.0)),
            "optimal_seconds": float(ca.get("optimal_seconds", -1.0)),
            "raw": dict(ca),
        }

    # -- the tuner's offline ranker ---------------------------------------

    def tile_penalty(self, tiles):
        """``tiles`` is [(size, alignment), ...]: each misaligned tile
        dim pays its padding waste — ceil(size/align)*align/size."""
        f = 1.0
        for size, align in tiles or ():
            size = max(1, int(size))
            align = max(1, int(align))
            padded = (size + align - 1) // align * align
            f *= padded / size
        return f

    def vmem_penalty(self, vmem_bytes, limit=VMEM_LIMIT_BYTES):
        """Over-budget configs would spill/fail on hardware: quadratic
        blow-up past the limit ranks them strictly behind every fitting
        config of the same alignment class."""
        if not vmem_bytes or vmem_bytes <= limit:
            return 1.0
        over = vmem_bytes / limit
        return 4.0 * over * over

    def grid_penalty(self, steps):
        """Mild per-grid-step overhead (dispatch + pipeline fill)."""
        return 1.0 + 1e-4 * max(0, int(steps or 0))

    def config_score(self, features, base_seconds=None):
        """Deterministic rank score for one candidate config. ``features``
        carries ``tiles`` [(size, align)], ``vmem_bytes``, ``steps`` (all
        optional). Lower is better; equal scores tie-break on space
        order upstream."""
        base = base_seconds if base_seconds and base_seconds > 0 else 1.0
        return (base
                * self.tile_penalty(features.get("tiles"))
                * self.vmem_penalty(features.get("vmem_bytes"))
                * self.grid_penalty(features.get("steps")))

    def rank_configs(self, features_list, base_seconds=None):
        """Indices of ``features_list`` sorted best-first (stable)."""
        scores = [self.config_score(f, base_seconds) for f in features_list]
        return sorted(range(len(scores)), key=lambda i: (scores[i], i))
