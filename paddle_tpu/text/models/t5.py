"""T5 encoder-decoder family.

Reference pairing: PaddleNLP t5/modeling.py (the reference repo's NLP zoo
provides T5 for seq2seq). TPU-first notes: scale-only RMS layer norm in
fp32, relative-position buckets computed once per length pair (static under
jit), attention through the shared sdpa path where unbiased; everything
traces into one XLA program.

Numerics verified against transformers.T5ForConditionalGeneration
(tests/test_hf_interop.py).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ...nn import Embedding, Linear
from ...nn import functional as F
from ...nn.layer_base import Layer
from ...nn.layer.container import LayerList
from ...tensor import Tensor, apply
from ...tensor_ops.manipulation import reshape


@dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"  # or "gated-gelu"
    tie_word_embeddings: bool = True
    pad_token_id: int = 0
    decoder_start_token_id: int = 0
    dtype: str = "float32"


T5_TINY = T5Config(vocab_size=256, d_model=64, d_kv=16, d_ff=128,
                   num_layers=2, num_decoder_layers=2, num_heads=4)


class T5LayerNorm(Layer):
    """Scale-only RMS norm (no mean subtraction, no bias)."""

    def __init__(self, d, eps=1e-6):
        super().__init__()
        from ...nn.initializer import Constant
        self.weight = self.create_parameter(
            (d,), default_initializer=Constant(1.0))
        self.eps = eps

    def forward(self, x):
        def f(a, w):
            af = a.astype(jnp.float32)
            var = jnp.mean(af * af, axis=-1, keepdims=True)
            return (af * jax.lax.rsqrt(var + self.eps)).astype(a.dtype) * w
        return apply(f, x, self.weight)


def _rel_bucket(rel_pos, bidirectional, num_buckets, max_distance):
    """HF-compatible relative position bucketing (T5 paper)."""
    ret = jnp.zeros_like(rel_pos)
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-9)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


class T5Attention(Layer):
    def __init__(self, c: T5Config, has_rel_bias=False, bidirectional=True):
        super().__init__()
        inner = c.num_heads * c.d_kv
        self.q = Linear(c.d_model, inner, bias_attr=False)
        self.k = Linear(c.d_model, inner, bias_attr=False)
        self.v = Linear(c.d_model, inner, bias_attr=False)
        self.o = Linear(inner, c.d_model, bias_attr=False)
        self.n_heads = c.num_heads
        self.d_kv = c.d_kv
        self.has_rel_bias = has_rel_bias
        self.bidirectional = bidirectional
        self.num_buckets = c.relative_attention_num_buckets
        self.max_distance = c.relative_attention_max_distance
        if has_rel_bias:
            self.relative_attention_bias = Embedding(self.num_buckets,
                                                     c.num_heads)

    def _bias(self, qlen, klen):
        ctx = jnp.arange(qlen)[:, None]
        mem = jnp.arange(klen)[None, :]
        buckets = _rel_bucket(mem - ctx, self.bidirectional,
                              self.num_buckets, self.max_distance)

        def f(table):
            return jnp.transpose(table[buckets], (2, 0, 1))[None]  # [1,H,q,k]
        return apply(f, self.relative_attention_bias.weight)

    def forward(self, x, kv=None, bias=None, causal=False):
        b, ql, _ = x.shape
        kv = x if kv is None else kv
        kl = kv.shape[1]
        q = reshape(self.q(x), (b, ql, self.n_heads, self.d_kv))
        k = reshape(self.k(kv), (b, kl, self.n_heads, self.d_kv))
        v = reshape(self.v(kv), (b, kl, self.n_heads, self.d_kv))

        def f(q, k, v, *maybe_bias):
            qh = jnp.swapaxes(q, 1, 2)
            kh = jnp.swapaxes(k, 1, 2)
            vh = jnp.swapaxes(v, 1, 2)
            # T5: NO 1/sqrt(d) scaling
            s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                           preferred_element_type=jnp.float32)
            if maybe_bias:
                s = s + maybe_bias[0].astype(jnp.float32)
            if causal:
                cm = jnp.tril(jnp.ones((s.shape[-2], s.shape[-1]), bool),
                              k=s.shape[-1] - s.shape[-2])
                s = jnp.where(cm, s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(qh.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
            return jnp.swapaxes(o, 1, 2).reshape(b, ql, -1)

        args = (q, k, v) + ((bias,) if bias is not None else ())
        return self.o(apply(f, *args))


class T5FF(Layer):
    def __init__(self, c: T5Config):
        super().__init__()
        self.gated = c.feed_forward_proj.startswith("gated")
        if self.gated:
            self.wi_0 = Linear(c.d_model, c.d_ff, bias_attr=False)
            self.wi_1 = Linear(c.d_model, c.d_ff, bias_attr=False)
        else:
            self.wi = Linear(c.d_model, c.d_ff, bias_attr=False)
        self.wo = Linear(c.d_ff, c.d_model, bias_attr=False)

    def forward(self, x):
        if self.gated:
            return self.wo(F.gelu(self.wi_0(x)) * self.wi_1(x))
        return self.wo(F.relu(self.wi(x)))


class T5Block(Layer):
    def __init__(self, c: T5Config, is_decoder, has_rel_bias):
        super().__init__()
        self.is_decoder = is_decoder
        self.ln1 = T5LayerNorm(c.d_model, c.layer_norm_epsilon)
        self.self_attn = T5Attention(c, has_rel_bias,
                                     bidirectional=not is_decoder)
        if is_decoder:
            self.ln_cross = T5LayerNorm(c.d_model, c.layer_norm_epsilon)
            self.cross_attn = T5Attention(c, False)
        self.ln2 = T5LayerNorm(c.d_model, c.layer_norm_epsilon)
        self.ff = T5FF(c)

    def forward(self, x, enc=None, self_bias=None):
        x = x + self.self_attn(self.ln1(x), bias=self_bias,
                               causal=self.is_decoder)
        if self.is_decoder and enc is not None:
            x = x + self.cross_attn(self.ln_cross(x), kv=enc)
        return x + self.ff(self.ln2(x))


class T5Stack(Layer):
    def __init__(self, c: T5Config, is_decoder, n_layers):
        super().__init__()
        self.is_decoder = is_decoder
        self.blocks = LayerList([
            T5Block(c, is_decoder, has_rel_bias=(i == 0))
            for i in range(n_layers)])
        self.final_layer_norm = T5LayerNorm(c.d_model, c.layer_norm_epsilon)

    def forward(self, x, enc=None):
        qlen = x.shape[1]
        bias = self.blocks[0].self_attn._bias(qlen, qlen)
        for blk in self.blocks:
            x = blk(x, enc=enc, self_bias=bias)
        return self.final_layer_norm(x)


class T5Model(Layer):
    def __init__(self, config: T5Config = T5Config()):
        super().__init__()
        self.config = config
        self.shared = Embedding(config.vocab_size, config.d_model)
        self.encoder = T5Stack(config, False, config.num_layers)
        self.decoder = T5Stack(config, True, config.num_decoder_layers)
        if config.dtype == "bfloat16":
            self.to(dtype="bfloat16")

    def forward(self, input_ids, decoder_input_ids):
        enc = self.encoder(self.shared(input_ids))
        dec = self.decoder(self.shared(decoder_input_ids), enc=enc)
        return dec, enc


class T5ForConditionalGeneration(Layer):
    def __init__(self, config: T5Config = T5Config()):
        super().__init__()
        self.config = config
        self.t5 = T5Model(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.d_model, config.vocab_size,
                                  bias_attr=False)

    def _shift_right(self, labels):
        def f(lab):
            shifted = jnp.roll(lab, 1, axis=-1)
            shifted = shifted.at[:, 0].set(
                self.config.decoder_start_token_id)
            return jnp.where(shifted == -100, self.config.pad_token_id,
                             shifted)
        return apply(f, labels)

    def forward(self, input_ids, decoder_input_ids=None, labels=None):
        c = self.config
        if decoder_input_ids is None:
            if labels is None:
                raise ValueError("need decoder_input_ids or labels")
            decoder_input_ids = self._shift_right(labels)
        dec, _ = self.t5(input_ids, decoder_input_ids)
        if c.tie_word_embeddings:
            from ...tensor_ops.math import matmul
            dec = dec * (c.d_model ** -0.5)
            logits = matmul(dec, self.t5.shared.weight, transpose_y=True)
        else:
            logits = self.lm_head(dec)
        if labels is not None:
            return F.cross_entropy(
                reshape(logits, (-1, c.vocab_size)).astype("float32"),
                reshape(labels, (-1,)), ignore_index=-100)
        return logits

    def generate(self, input_ids, max_new_tokens=32, eos_token_id=1,
                 name=None):
        """Greedy seq2seq decode (HF t5.generate greedy analog): ONE jitted
        program — the decoder runs on a padded [B, max_new] buffer inside a
        lax.scan, masking future positions, so shapes stay static (no
        per-length recompiles). O(n^2) decoder compute; fine at seq2seq
        generation lengths."""
        import jax
        import jax.numpy as jnp

        c = self.config
        ids = input_ids._data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        params = dict(self.named_parameters())
        pv = {k: p._data for k, p in params.items()}
        from ...autograd.tape import functional_mode
        from ...jit.api import _swap_params

        M = int(max_new_tokens)

        def run(pv, ids):
            with functional_mode(), _swap_params(params, pv):
                B = ids.shape[0]
                dec = jnp.full((B, M + 1), c.pad_token_id, jnp.int32)
                dec = dec.at[:, 0].set(c.decoder_start_token_id)
                done0 = jnp.zeros((B,), bool)

                def step(carry, t):
                    dec, done = carry
                    logits = self(Tensor(ids),
                                  decoder_input_ids=Tensor(dec))._data
                    nxt = jnp.argmax(
                        logits.astype(jnp.float32), axis=-1)
                    tok = jnp.take_along_axis(
                        nxt, t[None, None].repeat(B, 0), axis=1)[:, 0]
                    tok = tok.astype(jnp.int32)
                    if eos_token_id is not None:
                        tok = jnp.where(done, eos_token_id, tok)
                        done = jnp.logical_or(done, tok == eos_token_id)
                    dec = dec.at[:, t + 1].set(tok)
                    return (dec, done), None

                (dec, _), _ = jax.lax.scan(step, (dec, done0),
                                           jnp.arange(M))
                return dec

        return Tensor(jax.jit(run)(pv, ids))
