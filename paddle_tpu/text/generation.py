"""Autoregressive generation with a static KV cache.

Reference pairing: PaddleNLP's GenerationMixin (model.generate: greedy /
sampling with top-k/top-p, eos early-exit) driving the reference's
incremental decode. TPU-native design: ONE jitted program — prefill runs
the model's normal forward over the prompt, then `lax.scan` decodes
max_new_tokens steps against a PREALLOCATED [layers, B, total_len, kv, hd]
cache (static shapes: no per-step recompilation, no concat growth), with
sampling and eos masking inside the scan.

The per-layer prefill/decode bodies (`_llama_prefill_layer`,
`_llama_decode_layer`, `_gpt_prefill_layer`, `_gpt_decode_layer`) are
module-level and parameterized on per-row cache/rotary positions: batch
``generate()``, beam search AND ``paddle_tpu.serving.Engine`` all trace
the same python, so there is exactly one lowering of the decode math to
keep conformant.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .models.llama import _rope


def _stacked_weights(model):
    """Stack per-layer decoder weights of a LlamaForCausalLM into
    [L, ...] arrays (host-side, once per generate call)."""
    layers = model.llama.layers
    def st(get):
        return jnp.stack([get(l) for l in layers])
    w = {
        "wq": st(lambda l: l.self_attn.q_proj.weight._data),
        "wk": st(lambda l: l.self_attn.k_proj.weight._data),
        "wv": st(lambda l: l.self_attn.v_proj.weight._data),
        "wo": st(lambda l: l.self_attn.o_proj.weight._data),
        "wg": st(lambda l: l.mlp.gate_proj.weight._data),
        "wu": st(lambda l: l.mlp.up_proj.weight._data),
        "wd": st(lambda l: l.mlp.down_proj.weight._data),
        "ln1": st(lambda l: l.input_layernorm.weight._data),
        "ln2": st(lambda l: l.post_attention_layernorm.weight._data),
    }
    w["embed"] = model.llama.embed_tokens.weight._data
    w["norm"] = model.llama.norm.weight._data
    w["head"] = (model.llama.embed_tokens.weight._data.T if model.tie
                 else model.lm_head.weight._data)
    return w


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _rope_rows(q, k, pos, theta, dtype):
    """Rotary embedding for one-token-per-row decode: q, k [B, 1, H, D],
    pos [B] (each row may sit at a different position)."""
    d = q.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    freqs = pos[:, None].astype(jnp.float32) * inv_freq[None, :]  # [B, D/2]
    cos = jnp.cos(freqs)[:, None, None, :]
    sin = jnp.sin(freqs)[:, None, None, :]

    def rot(x):
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                              axis=-1)
        return out.astype(dtype)

    return rot(q), rot(k)


def _nucleus_filter(logits, top_p):
    """Top-p (nucleus) mask: keep exactly the smallest set of tokens
    whose cumulative probability reaches top_p (ties broken by sort
    order; the highest-prob token is always kept, even for top_p=0)."""
    order = jnp.argsort(-logits, axis=-1)          # descending
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = cum_excl < top_p
    keep_sorted = keep_sorted.at[..., 0].set(True)  # argmax survives
    inv = jnp.argsort(order, axis=-1)               # undo the sort
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    return jnp.where(keep, logits, -jnp.inf)


def _filter_logits(logits, temperature, do_sample, top_k, top_p):
    """Temperature / top-k / top-p filtering shared by batch generate()
    and the serving engine. logits [B, V]; temperature scalar or
    per-row [B]."""
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    if t.ndim == 1:
        t = t[:, None]
    logits = logits.astype(jnp.float32) / t
    if do_sample and top_k:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if do_sample and top_p is not None and top_p < 1.0:
        logits = _nucleus_filter(logits, top_p)
    return logits


def _prompt_mask(ids, pad_token_id, attention_mask):
    """[B, L0] int32 prefix mask (1 = real token) for right-padded
    prompts. An explicit attention_mask wins; otherwise everything up to
    the last non-pad token is real (a pad_token_id occurring inside the
    prompt is kept as a real token)."""
    if attention_mask is not None:
        am = attention_mask._data if isinstance(attention_mask, Tensor) \
            else jnp.asarray(attention_mask)
        return am.astype(jnp.int32)
    if pad_token_id is None:
        return jnp.ones_like(ids)
    L0 = ids.shape[1]
    nonpad = ids != pad_token_id
    plen = jnp.max(jnp.where(nonpad, jnp.arange(1, L0 + 1)[None, :], 0),
                   axis=1)
    return (jnp.arange(L0)[None, :] < plen[:, None]).astype(jnp.int32)


# ---------------------------------------------------------------------------
# shared per-layer bodies (Llama)
# ---------------------------------------------------------------------------

_LLAMA_STACK_KEYS = ("wq", "wk", "wv", "wo", "wg", "wu", "wd", "ln1", "ln2")


def _llama_prefill_layer(x, lw, pos, *, n_heads, n_kv, eps, theta):
    """One Llama decoder layer over a full [B, L] prompt (causal).
    Returns (x, (k, v)) with k/v [B, L, n_kv, hd] for the KV cache."""
    B, L, h = x.shape
    hd = h // n_heads
    dt = x.dtype
    h1 = _rms(x, lw["ln1"], eps)
    q = (h1 @ lw["wq"]).reshape(B, L, n_heads, hd)
    k = (h1 @ lw["wk"]).reshape(B, L, n_kv, hd)
    v = (h1 @ lw["wv"]).reshape(B, L, n_kv, hd)
    q, k = _rope(q, k, pos, theta, dt)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.repeat(jnp.swapaxes(k, 1, 2), n_heads // n_kv, axis=1)
    vh = jnp.repeat(jnp.swapaxes(v, 1, 2), n_heads // n_kv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    cm = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(cm, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = jnp.swapaxes(o, 1, 2).reshape(B, L, h)
    x = x + o @ lw["wo"]
    h2 = _rms(x, lw["ln2"], eps)
    x = x + (jax.nn.silu(h2 @ lw["wg"]) * (h2 @ lw["wu"])) @ lw["wd"]
    return x, (k, v)


def _llama_decode_layer(xt, lw, kc_l, vc_l, write_idx, rope_pos, key_mask,
                        *, n_heads, n_kv, eps, theta):
    """One Llama decoder layer advancing every row one token.

    xt [B, 1, h]; kc_l/vc_l [B, T, n_kv, hd]; write_idx [B] — the cache
    line each row's new K/V lands in; rope_pos [B] — each row's rotary
    position (differs from write_idx only for right-padded prompts);
    key_mask [B, T] bool or None — extra attendable-position mask on top
    of the causal ``<= write_idx`` bound (False = never attend; hides
    prompt padding lines).
    """
    B, T = kc_l.shape[0], kc_l.shape[1]
    h = xt.shape[-1]
    hd = h // n_heads
    dt = xt.dtype
    h1 = _rms(xt, lw["ln1"], eps)
    q = (h1 @ lw["wq"]).reshape(B, 1, n_heads, hd)
    k = (h1 @ lw["wk"]).reshape(B, 1, n_kv, hd)
    v = (h1 @ lw["wv"]).reshape(B, 1, n_kv, hd)
    q, k = _rope_rows(q, k, rope_pos, theta, dt)
    rows = jnp.arange(B)
    kc_l = kc_l.at[rows, write_idx].set(k[:, 0])
    vc_l = vc_l.at[rows, write_idx].set(v[:, 0])
    kh = jnp.repeat(kc_l, n_heads // n_kv, axis=2)       # [B, T, H, hd]
    vh = jnp.repeat(vc_l, n_heads // n_kv, axis=2)
    s = jnp.einsum("bhd,bthd->bht", q[:, 0], kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    valid = jnp.arange(T)[None, :] <= write_idx[:, None]
    if key_mask is not None:
        valid = jnp.logical_and(valid, key_mask)
    s = jnp.where(valid[:, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bht,bthd->bhd", p, vh).reshape(B, 1, h)
    xt2 = xt + o @ lw["wo"]
    h2 = _rms(xt2, lw["ln2"], eps)
    xt2 = xt2 + (jax.nn.silu(h2 @ lw["wg"]) * (h2 @ lw["wu"])) @ lw["wd"]
    return xt2, kc_l, vc_l


@functools.partial(jax.jit, static_argnames=(
    "n_heads", "n_kv", "eps", "theta", "max_new", "do_sample", "top_k",
    "eos_id", "top_p", "padded"))
def _generate_jit(w, input_ids, prompt_len_mask, key, *, n_heads, n_kv, eps,
                  theta, max_new, do_sample, top_k, eos_id, temperature,
                  top_p=None, padded=False):
    """input_ids: [B, L0] right-padded prompt; prompt_len_mask [B, L0]
    (1 = real token). With padded=True the right-padding semantics are
    active: per-row rotary positions continue from the prompt length and
    pad KV lines are masked out of decode attention. Returns
    [B, L0 + max_new]."""
    B, L0 = input_ids.shape
    h = w["embed"].shape[1]
    hd = h // n_heads
    T = L0 + max_new
    nL = w["wq"].shape[0]
    dt = w["embed"].dtype

    # ---- prefill: full causal pass over the (padded) prompt ----
    x = jnp.take(w["embed"], input_ids, axis=0)
    pos = jnp.arange(L0)
    kcache = jnp.zeros((nL, B, T, n_kv, hd), dt)
    vcache = jnp.zeros((nL, B, T, n_kv, hd), dt)

    stack = {k: w[k] for k in _LLAMA_STACK_KEYS}

    def one_prefill(x, lw):
        return _llama_prefill_layer(x, lw, pos, n_heads=n_heads, n_kv=n_kv,
                                    eps=eps, theta=theta)

    x, kvs = jax.lax.scan(one_prefill, x, stack)
    kcache = kcache.at[:, :, :L0].set(kvs[0])
    vcache = vcache.at[:, :, :L0].set(kvs[1])

    # last real token index per row
    prompt_len = jnp.sum(prompt_len_mask, axis=1).astype(jnp.int32)
    last_idx = prompt_len - 1
    hidden = _rms(x, w["norm"], eps)
    logits0 = jnp.take_along_axis(
        hidden, last_idx[:, None, None].repeat(h, 2), axis=1)[:, 0] @ w["head"]

    def sample(logits, key):
        logits = _filter_logits(logits, temperature, do_sample, top_k, top_p)
        if not do_sample:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    key, sk = jax.random.split(key)
    tok0 = sample(logits0, sk)

    out = jnp.zeros((B, max_new), jnp.int32)
    out = out.at[:, 0].set(tok0)
    done0 = (tok0 == eos_id) if eos_id is not None else jnp.zeros(
        (B,), bool)

    # pad lines of the prompt must never be attended; generated lines
    # (>= L0) are gated by the causal <= write_idx bound alone
    key_mask = (jnp.concatenate(
        [prompt_len_mask.astype(bool), jnp.ones((B, max_new), bool)],
        axis=1) if padded else None)

    def decode_step(carry, i):
        tok, cur_pos, kcache, vcache, key, done = carry
        xt = jnp.take(w["embed"], tok, axis=0)[:, None]          # [B,1,h]
        write_idx = jnp.full((B,), cur_pos, jnp.int32)
        rope_pos = prompt_len + (i - 1) if padded else write_idx

        def one(cx, lw_kv):
            xt2, kc_l, vc_l = _llama_decode_layer(
                cx["x"], lw_kv, lw_kv["kc"], lw_kv["vc"], write_idx,
                rope_pos, key_mask, n_heads=n_heads, n_kv=n_kv, eps=eps,
                theta=theta)
            return {"x": xt2}, (kc_l, vc_l)

        lw_kv = dict(stack)
        lw_kv["kc"] = kcache
        lw_kv["vc"] = vcache
        cx, (kcache, vcache) = jax.lax.scan(one, {"x": xt}, lw_kv)
        hidden = _rms(cx["x"][:, 0], w["norm"], eps)
        logits = hidden @ w["head"]
        key, sk = jax.random.split(key)
        nxt = sample(logits, sk)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = jnp.logical_or(done, nxt == eos_id)
        return (nxt, cur_pos + 1, kcache, vcache, key, done), nxt

    if max_new > 1:
        carry = (tok0, jnp.int32(L0), kcache, vcache, key, done0)
        _, toks = jax.lax.scan(decode_step, carry,
                               jnp.arange(1, max_new))
        out = out.at[:, 1:].set(jnp.swapaxes(toks, 0, 1))
    return jnp.concatenate([input_ids, out], axis=1)


# ---------------------------------------------------------------------------
# paged-KV bodies (serving engine): decode attention gathers K/V through
# per-slot block tables; prefill/chunk writes are block-aligned scatters
# into the shared pool (masked writes redirect to the reserved trash
# block). Module-level like the slot bodies: one lowering per shape.
# ---------------------------------------------------------------------------


def _paged_view(pool_l, tables, block_size):
    """Gather contiguous per-slot K or V views through block tables:
    pool_l [n_blocks, bs, kv, hd], tables [S, mb] -> [S, mb*bs, kv, hd]
    (view index == logical position; unused table entries point at the
    trash block and sit beyond the causal bound)."""
    v = pool_l[tables]                       # [S, mb, bs, kv, hd]
    S, mb = tables.shape
    return v.reshape(S, mb * block_size, pool_l.shape[-2],
                     pool_l.shape[-1])


def _paged_decode_attention(q, kc_pool, vc_pool, tables, write_pos,
                            block_size, flash, dt):
    """One-token paged attention: q [S, H, hd] over the pool through
    block tables. ``flash=True`` runs the tuner-registered pallas
    flash-decode kernel (block DMA straight off the table rows + online
    softmax — no [S, T] gather materializes; interpret mode on CPU);
    False keeps the gathered XLA form. Both share the causal contract
    ``view position <= write_pos``; the flash output is token-identical,
    not bitwise (online-softmax reduction order)."""
    S, H, hd = q.shape
    n_kv = kc_pool.shape[2]
    if flash:
        from ..ops.pallas.flash_decode import flash_decode
        return flash_decode(
            q, kc_pool, vc_pool, tables, write_pos,
            interpret=jax.default_backend() == "cpu").astype(dt)
    kview = _paged_view(kc_pool, tables, block_size)   # [S, T, n_kv, hd]
    vview = _paged_view(vc_pool, tables, block_size)
    kh = jnp.repeat(kview, H // n_kv, axis=2)
    vh = jnp.repeat(vview, H // n_kv, axis=2)
    s = jnp.einsum("bhd,bthd->bht", q, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    T = kview.shape[1]
    valid = jnp.arange(T)[None, :] <= write_pos[:, None]
    s = jnp.where(valid[:, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    return jnp.einsum("bht,bthd->bhd", p, vh)


def _llama_decode_layer_paged(xt, lw, kc_pool, vc_pool, tables, dest,
                              write_pos, rope_pos, *, n_heads, n_kv, eps,
                              theta, block_size, flash_decode=False):
    """One Llama decoder layer advancing every slot one token against
    the paged pool: the new K/V scatters to flat pool index ``dest``
    (trash-redirected for inactive rows), then attention gathers each
    slot's view through its block-table row. kc_pool/vc_pool
    [n_blocks, bs, n_kv, hd] (one layer); tables [S, mb]; dest [S];
    write_pos/rope_pos [S]."""
    S = xt.shape[0]
    h = xt.shape[-1]
    hd = h // n_heads
    dt = xt.dtype
    h1 = _rms(xt, lw["ln1"], eps)
    q = (h1 @ lw["wq"]).reshape(S, 1, n_heads, hd)
    k = (h1 @ lw["wk"]).reshape(S, 1, n_kv, hd)
    v = (h1 @ lw["wv"]).reshape(S, 1, n_kv, hd)
    q, k = _rope_rows(q, k, rope_pos, theta, dt)
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    kc_pool = kc_pool.reshape(nb * bs, n_kv, hd).at[dest].set(
        k[:, 0]).reshape(nb, bs, n_kv, hd)
    vc_pool = vc_pool.reshape(nb * bs, n_kv, hd).at[dest].set(
        v[:, 0]).reshape(nb, bs, n_kv, hd)
    o = _paged_decode_attention(q[:, 0], kc_pool, vc_pool, tables,
                                write_pos, block_size, flash_decode,
                                dt).reshape(S, 1, h)
    xt2 = xt + o @ lw["wo"]
    h2 = _rms(xt2, lw["ln2"], eps)
    xt2 = xt2 + (jax.nn.silu(h2 @ lw["wg"]) * (h2 @ lw["wu"])) @ lw["wd"]
    return xt2, kc_pool, vc_pool


def _gpt_decode_layer_paged(xt, lw, kc_pool, vc_pool, tables, dest,
                            write_pos, *, n_heads, block_size,
                            flash_decode=False):
    """GPT block, paged decode (learned positions enter at the
    embedding; only the pool write/gather differs from the slot body)."""
    S = xt.shape[0]
    h = xt.shape[-1]
    hd = h // n_heads
    dt = xt.dtype
    hN = _ln(xt, lw["ln1w"], lw["ln1b"])
    qkv = hN @ lw["wqkv"] + lw["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(S, 1, n_heads, hd)
    k = k.reshape(S, 1, n_heads, hd)
    v = v.reshape(S, 1, n_heads, hd)
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    kc_pool = kc_pool.reshape(nb * bs, n_heads, hd).at[dest].set(
        k[:, 0]).reshape(nb, bs, n_heads, hd)
    vc_pool = vc_pool.reshape(nb * bs, n_heads, hd).at[dest].set(
        v[:, 0]).reshape(nb, bs, n_heads, hd)
    o = _paged_decode_attention(q[:, 0], kc_pool, vc_pool, tables,
                                write_pos, block_size, flash_decode,
                                dt).reshape(S, 1, h)
    xt2 = xt + o @ lw["wproj"] + lw["bproj"]
    h2 = _ln(xt2, lw["ln2w"], lw["ln2b"])
    xt2 = xt2 + jax.nn.gelu(h2 @ lw["wfc1"] + lw["bfc1"],
                            approximate=False) @ lw["wfc2"] + lw["bfc2"]
    return xt2, kc_pool, vc_pool


def _llama_chunk_layer(x, lw, kc_pool, vc_pool, table_row, gpos, wdest, *,
                       n_heads, n_kv, eps, theta, block_size):
    """One Llama layer over one block-aligned prefill CHUNK of a single
    slot: x [1, C, h] at global positions ``gpos`` [C]; the chunk's K/V
    scatter to flat pool indices ``wdest`` [C] (shared-prefix / pad
    positions trash-redirected), then the chunk rows attend to the
    slot's full gathered view (earlier chunks + this one) under the
    causal bound ``view_pos <= gpos``."""
    B, C, h = x.shape
    hd = h // n_heads
    dt = x.dtype
    h1 = _rms(x, lw["ln1"], eps)
    q = (h1 @ lw["wq"]).reshape(B, C, n_heads, hd)
    k = (h1 @ lw["wk"]).reshape(B, C, n_kv, hd)
    v = (h1 @ lw["wv"]).reshape(B, C, n_kv, hd)
    q, k = _rope(q, k, gpos, theta, dt)
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    kc_pool = kc_pool.reshape(nb * bs, n_kv, hd).at[wdest].set(
        k[0]).reshape(nb, bs, n_kv, hd)
    vc_pool = vc_pool.reshape(nb * bs, n_kv, hd).at[wdest].set(
        v[0]).reshape(nb, bs, n_kv, hd)
    kview = _paged_view(kc_pool, table_row[None], block_size)  # [1,T,kv,hd]
    vview = _paged_view(vc_pool, table_row[None], block_size)
    qh = jnp.swapaxes(q, 1, 2)                                 # [1,H,C,hd]
    kh = jnp.repeat(jnp.swapaxes(kview, 1, 2), n_heads // n_kv, axis=1)
    vh = jnp.repeat(jnp.swapaxes(vview, 1, 2), n_heads // n_kv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    T = kview.shape[1]
    cm = jnp.arange(T)[None, :] <= gpos[:, None]               # [C, T]
    s = jnp.where(cm[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = jnp.swapaxes(o, 1, 2).reshape(B, C, h)
    x = x + o @ lw["wo"]
    h2 = _rms(x, lw["ln2"], eps)
    x = x + (jax.nn.silu(h2 @ lw["wg"]) * (h2 @ lw["wu"])) @ lw["wd"]
    return x, kc_pool, vc_pool


def _gpt_chunk_layer(x, lw, kc_pool, vc_pool, table_row, gpos, wdest, *,
                     n_heads, block_size):
    """GPT block over one prefill chunk (positions via wpe upstream)."""
    B, C, h = x.shape
    hd = h // n_heads
    dt = x.dtype
    hN = _ln(x, lw["ln1w"], lw["ln1b"])
    qkv = hN @ lw["wqkv"] + lw["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, C, n_heads, hd)
    k = k.reshape(B, C, n_heads, hd)
    v = v.reshape(B, C, n_heads, hd)
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    kc_pool = kc_pool.reshape(nb * bs, n_heads, hd).at[wdest].set(
        k[0]).reshape(nb, bs, n_heads, hd)
    vc_pool = vc_pool.reshape(nb * bs, n_heads, hd).at[wdest].set(
        v[0]).reshape(nb, bs, n_heads, hd)
    kview = _paged_view(kc_pool, table_row[None], block_size)
    vview = _paged_view(vc_pool, table_row[None], block_size)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(kview, 1, 2)
    vh = jnp.swapaxes(vview, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    T = kview.shape[1]
    cm = jnp.arange(T)[None, :] <= gpos[:, None]
    s = jnp.where(cm[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = jnp.swapaxes(o, 1, 2).reshape(B, C, h)
    x = x + o @ lw["wproj"] + lw["bproj"]
    h2 = _ln(x, lw["ln2w"], lw["ln2b"])
    x = x + jax.nn.gelu(h2 @ lw["wfc1"] + lw["bfc1"],
                        approximate=False) @ lw["wfc2"] + lw["bfc2"]
    return x, kc_pool, vc_pool


def _llama_verify_layer(x, lw, kc_pool, vc_pool, table_row, gpos, wdest, *,
                        n_heads, n_kv, eps, theta, block_size):
    """One Llama layer over a speculative VERIFY chunk: the draft's k+1
    candidate tokens of one slot at decode positions ``gpos``, candidate
    K/V scattered through the slot's block table (``wdest`` trash-
    redirects positions past the effective draft width), attention over
    the slot's gathered view under the causal bound. Deliberately THE
    chunk-layer math — verification is a k-token chunk scoring k+1
    positions, so there is one body to keep conformant with prefill and
    one extra lowering total."""
    return _llama_chunk_layer(x, lw, kc_pool, vc_pool, table_row, gpos,
                              wdest, n_heads=n_heads, n_kv=n_kv, eps=eps,
                              theta=theta, block_size=block_size)


def _gpt_verify_layer(x, lw, kc_pool, vc_pool, table_row, gpos, wdest, *,
                      n_heads, block_size):
    """GPT block over a speculative verify chunk (see
    :func:`_llama_verify_layer`): shares the chunk-layer math."""
    return _gpt_chunk_layer(x, lw, kc_pool, vc_pool, table_row, gpos,
                            wdest, n_heads=n_heads, block_size=block_size)


# ---------------------------------------------------------------------------
# tensor-parallel bodies (serving engine, paged KV): the SAME math as the
# single-device bodies above with the weights column-/row-parallel over a
# "tp" mesh axis — each device computes its head/column shard locally and
# the two row-parallel projections (o-proj, down-proj) reassemble the
# replicated activations through ppermute-pipelined collective-matmuls
# (distributed.collective_matmul), so no collective serializes after a
# dot. These run INSIDE shard_map: every weight leaf and the KV pool
# arrive as LOCAL shards; activations between layers stay replicated.
# ---------------------------------------------------------------------------

from ..distributed.collective_matmul import (matmul_allgather,  # noqa: E402
                                             ring_rowparallel_matmul)

_TP_AXIS = "tp"


def _llama_tp_specs():
    """PartitionSpec per stacked-Llama weight key over the ``tp`` axis:
    column-parallel QKV/gate-up (output dim sharded), row-parallel
    o-/down-proj (contraction dim sharded), vocab-sharded head; norms
    and the embedding table replicated."""
    from jax.sharding import PartitionSpec as P
    col, row = P(None, None, _TP_AXIS), P(None, _TP_AXIS, None)
    return {"wq": col, "wk": col, "wv": col, "wg": col, "wu": col,
            "wo": row, "wd": row, "ln1": P(), "ln2": P(),
            "embed": P(), "norm": P(), "head": P(None, _TP_AXIS)}


def _gpt_tp_specs():
    """GPT weight placement: the fused qkv columns are pre-permuted to
    device-major ``[q_d | k_d | v_d]`` order (see
    :func:`_gpt_qkv_tp_permutation`) so a contiguous tp shard carries
    one head-slice of each of q, k, v; row-parallel proj/fc2 biases stay
    replicated (added once, after the reduce)."""
    from jax.sharding import PartitionSpec as P
    col, row = P(None, None, _TP_AXIS), P(None, _TP_AXIS, None)
    return {"wqkv": col, "bqkv": P(None, _TP_AXIS),
            "wproj": row, "bproj": P(),
            "wfc1": col, "bfc1": P(None, _TP_AXIS),
            "wfc2": row, "bfc2": P(),
            "ln1w": P(), "ln1b": P(), "ln2w": P(), "ln2b": P(),
            "wte": P(), "wpe": P(), "lnfw": P(), "lnfb": P(),
            "head": P(None, _TP_AXIS)}


def _gpt_qkv_tp_permutation(h, tp):
    """Column permutation mapping the fused ``[q | k | v]`` qkv layout to
    device-major ``[q_0 k_0 v_0 | q_1 k_1 v_1 | ...]``: sharding the
    permuted last dim over ``tp`` then hands each device its own head
    slice of all three projections as one contiguous block."""
    import numpy as np
    hc = h // tp
    idx = []
    for d in range(tp):
        for blk in range(3):
            idx.append(np.arange(blk * h + d * hc, blk * h + (d + 1) * hc))
    return np.concatenate(idx)


def _llama_prefill_layer_tp(x, lw, pos, *, n_heads, n_kv, eps, theta, tp):
    """TP variant of :func:`_llama_prefill_layer`: local head shards for
    attention, collective-matmul for the o-projection and down-proj.
    Returns (x_replicated, (k_local, v_local)) — k/v carry this device's
    ``n_kv // tp`` head shard for the sharded KV pool."""
    B, L, h = x.shape
    hd = h // n_heads
    hl, kvl = n_heads // tp, n_kv // tp
    dt = x.dtype
    h1 = _rms(x, lw["ln1"], eps)
    q = (h1 @ lw["wq"]).reshape(B, L, hl, hd)
    k = (h1 @ lw["wk"]).reshape(B, L, kvl, hd)
    v = (h1 @ lw["wv"]).reshape(B, L, kvl, hd)
    q, k = _rope(q, k, pos, theta, dt)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.repeat(jnp.swapaxes(k, 1, 2), n_heads // n_kv, axis=1)
    vh = jnp.repeat(jnp.swapaxes(v, 1, 2), n_heads // n_kv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    cm = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(cm, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = jnp.swapaxes(o, 1, 2).reshape(B, L, h // tp)
    x = x + ring_rowparallel_matmul(o, lw["wo"], _TP_AXIS, tp)
    h2 = _rms(x, lw["ln2"], eps)
    act = jax.nn.silu(h2 @ lw["wg"]) * (h2 @ lw["wu"])
    x = x + ring_rowparallel_matmul(act, lw["wd"], _TP_AXIS, tp)
    return x, (k, v)


def _llama_decode_layer_paged_tp(xt, lw, kc_pool, vc_pool, tables, dest,
                                 write_pos, rope_pos, *, n_heads, n_kv,
                                 eps, theta, block_size, tp):
    """TP variant of :func:`_llama_decode_layer_paged`: the pool shards
    hold this device's kv-head slice, attention runs over the local head
    group, and the o-/down-projections are overlapped collective-matmuls
    (the activations they produce are replicated for the next layer)."""
    S = xt.shape[0]
    h = xt.shape[-1]
    hd = h // n_heads
    hl, kvl = n_heads // tp, n_kv // tp
    dt = xt.dtype
    h1 = _rms(xt, lw["ln1"], eps)
    q = (h1 @ lw["wq"]).reshape(S, 1, hl, hd)
    k = (h1 @ lw["wk"]).reshape(S, 1, kvl, hd)
    v = (h1 @ lw["wv"]).reshape(S, 1, kvl, hd)
    q, k = _rope_rows(q, k, rope_pos, theta, dt)
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    kc_pool = kc_pool.reshape(nb * bs, kvl, hd).at[dest].set(
        k[:, 0]).reshape(nb, bs, kvl, hd)
    vc_pool = vc_pool.reshape(nb * bs, kvl, hd).at[dest].set(
        v[:, 0]).reshape(nb, bs, kvl, hd)
    kview = _paged_view(kc_pool, tables, block_size)   # [S, T, kvl, hd]
    vview = _paged_view(vc_pool, tables, block_size)
    kh = jnp.repeat(kview, n_heads // n_kv, axis=2)
    vh = jnp.repeat(vview, n_heads // n_kv, axis=2)
    s = jnp.einsum("bhd,bthd->bht", q[:, 0], kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    T = kview.shape[1]
    valid = jnp.arange(T)[None, :] <= write_pos[:, None]
    s = jnp.where(valid[:, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bht,bthd->bhd", p, vh).reshape(S, 1, h // tp)
    xt2 = xt + ring_rowparallel_matmul(o, lw["wo"], _TP_AXIS, tp)
    h2 = _rms(xt2, lw["ln2"], eps)
    act = jax.nn.silu(h2 @ lw["wg"]) * (h2 @ lw["wu"])
    xt2 = xt2 + ring_rowparallel_matmul(act, lw["wd"], _TP_AXIS, tp)
    return xt2, kc_pool, vc_pool


def _llama_chunk_layer_tp(x, lw, kc_pool, vc_pool, table_row, gpos, wdest,
                          *, n_heads, n_kv, eps, theta, block_size, tp):
    """TP variant of :func:`_llama_chunk_layer` (one prefill chunk of
    one slot against the sharded pool)."""
    B, C, h = x.shape
    hd = h // n_heads
    hl, kvl = n_heads // tp, n_kv // tp
    dt = x.dtype
    h1 = _rms(x, lw["ln1"], eps)
    q = (h1 @ lw["wq"]).reshape(B, C, hl, hd)
    k = (h1 @ lw["wk"]).reshape(B, C, kvl, hd)
    v = (h1 @ lw["wv"]).reshape(B, C, kvl, hd)
    q, k = _rope(q, k, gpos, theta, dt)
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    kc_pool = kc_pool.reshape(nb * bs, kvl, hd).at[wdest].set(
        k[0]).reshape(nb, bs, kvl, hd)
    vc_pool = vc_pool.reshape(nb * bs, kvl, hd).at[wdest].set(
        v[0]).reshape(nb, bs, kvl, hd)
    kview = _paged_view(kc_pool, table_row[None], block_size)
    vview = _paged_view(vc_pool, table_row[None], block_size)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.repeat(jnp.swapaxes(kview, 1, 2), n_heads // n_kv, axis=1)
    vh = jnp.repeat(jnp.swapaxes(vview, 1, 2), n_heads // n_kv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    T = kview.shape[1]
    cm = jnp.arange(T)[None, :] <= gpos[:, None]
    s = jnp.where(cm[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = jnp.swapaxes(o, 1, 2).reshape(B, C, h // tp)
    x = x + ring_rowparallel_matmul(o, lw["wo"], _TP_AXIS, tp)
    h2 = _rms(x, lw["ln2"], eps)
    act = jax.nn.silu(h2 @ lw["wg"]) * (h2 @ lw["wu"])
    x = x + ring_rowparallel_matmul(act, lw["wd"], _TP_AXIS, tp)
    return x, kc_pool, vc_pool


def _gpt_prefill_layer_tp(x, lw, *, n_heads, tp):
    """TP variant of :func:`_gpt_prefill_layer` (device-major permuted
    qkv shard; row-parallel proj/fc2 add their bias after the reduce)."""
    B, L, h = x.shape
    hd = h // n_heads
    hl = n_heads // tp
    dt = x.dtype
    hN = _ln(x, lw["ln1w"], lw["ln1b"])
    qkv = hN @ lw["wqkv"] + lw["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, L, hl, hd)
    k = k.reshape(B, L, hl, hd)
    v = v.reshape(B, L, hl, hd)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    cm = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(cm, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = jnp.swapaxes(o, 1, 2).reshape(B, L, h // tp)
    x = x + ring_rowparallel_matmul(o, lw["wproj"], _TP_AXIS, tp) \
        + lw["bproj"]
    h2 = _ln(x, lw["ln2w"], lw["ln2b"])
    act = jax.nn.gelu(h2 @ lw["wfc1"] + lw["bfc1"], approximate=False)
    x = x + ring_rowparallel_matmul(act, lw["wfc2"], _TP_AXIS, tp) \
        + lw["bfc2"]
    return x, (k, v)


def _gpt_decode_layer_paged_tp(xt, lw, kc_pool, vc_pool, tables, dest,
                               write_pos, *, n_heads, block_size, tp):
    """TP variant of :func:`_gpt_decode_layer_paged`."""
    S = xt.shape[0]
    h = xt.shape[-1]
    hd = h // n_heads
    hl = n_heads // tp
    dt = xt.dtype
    hN = _ln(xt, lw["ln1w"], lw["ln1b"])
    qkv = hN @ lw["wqkv"] + lw["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(S, 1, hl, hd)
    k = k.reshape(S, 1, hl, hd)
    v = v.reshape(S, 1, hl, hd)
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    kc_pool = kc_pool.reshape(nb * bs, hl, hd).at[dest].set(
        k[:, 0]).reshape(nb, bs, hl, hd)
    vc_pool = vc_pool.reshape(nb * bs, hl, hd).at[dest].set(
        v[:, 0]).reshape(nb, bs, hl, hd)
    kview = _paged_view(kc_pool, tables, block_size)
    vview = _paged_view(vc_pool, tables, block_size)
    s = jnp.einsum("bhd,bthd->bht", q[:, 0], kview,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    T = kview.shape[1]
    valid = jnp.arange(T)[None, :] <= write_pos[:, None]
    s = jnp.where(valid[:, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bht,bthd->bhd", p, vview).reshape(S, 1, h // tp)
    xt2 = xt + ring_rowparallel_matmul(o, lw["wproj"], _TP_AXIS, tp) \
        + lw["bproj"]
    h2 = _ln(xt2, lw["ln2w"], lw["ln2b"])
    act = jax.nn.gelu(h2 @ lw["wfc1"] + lw["bfc1"], approximate=False)
    xt2 = xt2 + ring_rowparallel_matmul(act, lw["wfc2"], _TP_AXIS, tp) \
        + lw["bfc2"]
    return xt2, kc_pool, vc_pool


def _gpt_chunk_layer_tp(x, lw, kc_pool, vc_pool, table_row, gpos, wdest,
                        *, n_heads, block_size, tp):
    """TP variant of :func:`_gpt_chunk_layer`."""
    B, C, h = x.shape
    hd = h // n_heads
    hl = n_heads // tp
    dt = x.dtype
    hN = _ln(x, lw["ln1w"], lw["ln1b"])
    qkv = hN @ lw["wqkv"] + lw["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, C, hl, hd)
    k = k.reshape(B, C, hl, hd)
    v = v.reshape(B, C, hl, hd)
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    kc_pool = kc_pool.reshape(nb * bs, hl, hd).at[wdest].set(
        k[0]).reshape(nb, bs, hl, hd)
    vc_pool = vc_pool.reshape(nb * bs, hl, hd).at[wdest].set(
        v[0]).reshape(nb, bs, hl, hd)
    kview = _paged_view(kc_pool, table_row[None], block_size)
    vview = _paged_view(vc_pool, table_row[None], block_size)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(kview, 1, 2)
    vh = jnp.swapaxes(vview, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    T = kview.shape[1]
    cm = jnp.arange(T)[None, :] <= gpos[:, None]
    s = jnp.where(cm[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = jnp.swapaxes(o, 1, 2).reshape(B, C, h // tp)
    x = x + ring_rowparallel_matmul(o, lw["wproj"], _TP_AXIS, tp) \
        + lw["bproj"]
    h2 = _ln(x, lw["ln2w"], lw["ln2b"])
    act = jax.nn.gelu(h2 @ lw["wfc1"] + lw["bfc1"], approximate=False)
    x = x + ring_rowparallel_matmul(act, lw["wfc2"], _TP_AXIS, tp) \
        + lw["bfc2"]
    return x, kc_pool, vc_pool


# ---------------------------------------------------------------------------
# beam search (Llama decoder)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "n_heads", "n_kv", "eps", "theta", "max_new", "num_beams", "eos_id"))
def _beam_generate_jit(w, input_ids, *, n_heads, n_kv, eps, theta, max_new,
                       num_beams, eos_id, length_penalty):
    """Beam search with the same static cache design: beams fold into the
    batch dim; caches reorder by beam index each step (HF/PaddleNLP
    BeamSearchScorer semantics, length-penalized log-prob)."""
    B, L0 = input_ids.shape
    K = num_beams
    h = w["embed"].shape[1]
    hd = h // n_heads
    T = L0 + max_new
    nL = w["wq"].shape[0]
    dt = w["embed"].dtype
    NEG = jnp.float32(-1e9)

    # ---- prefill once per batch row, then tile to beams ----
    x = jnp.take(w["embed"], input_ids, axis=0)
    pos = jnp.arange(L0)
    stack = {k: w[k] for k in _LLAMA_STACK_KEYS}

    def one_prefill(x, lw):
        return _llama_prefill_layer(x, lw, pos, n_heads=n_heads, n_kv=n_kv,
                                    eps=eps, theta=theta)

    x, kvs = jax.lax.scan(one_prefill, x, stack)
    kcache = jnp.zeros((nL, B * K, T, n_kv, hd), dt)
    vcache = jnp.zeros_like(kcache)
    kcache = kcache.at[:, :, :L0].set(jnp.repeat(kvs[0], K, axis=1))
    vcache = vcache.at[:, :, :L0].set(jnp.repeat(kvs[1], K, axis=1))

    hidden = _rms(x[:, -1], w["norm"], eps)
    logp0 = jax.nn.log_softmax(
        (hidden @ w["head"]).astype(jnp.float32), axis=-1)   # [B, V]
    V = logp0.shape[-1]
    top0, tok0 = jax.lax.top_k(logp0, K)                     # [B, K]
    scores = top0                                            # [B, K]
    toks = jnp.zeros((B, K, max_new), jnp.int32).at[..., 0].set(tok0)
    done = (tok0 == eos_id) if eos_id is not None else jnp.zeros((B, K),
                                                                 bool)

    def decode_step(carry, i):
        toks, scores, cur_pos, kcache, vcache, done = carry
        tok = jax.lax.dynamic_index_in_dim(toks, i - 1, 2, False)  # [B,K]
        xt = jnp.take(w["embed"], tok.reshape(B * K), axis=0)[:, None]
        write_idx = jnp.full((B * K,), cur_pos, jnp.int32)

        def one(cx, lw_kv):
            xt2, kc_l, vc_l = _llama_decode_layer(
                cx["x"], lw_kv, lw_kv["kc"], lw_kv["vc"], write_idx,
                write_idx, None, n_heads=n_heads, n_kv=n_kv, eps=eps,
                theta=theta)
            return {"x": xt2}, (kc_l, vc_l)

        lw_kv = dict(stack)
        lw_kv["kc"] = kcache
        lw_kv["vc"] = vcache
        cx, (kcache, vcache) = jax.lax.scan(one, {"x": xt}, lw_kv)
        hidden = _rms(cx["x"][:, 0], w["norm"], eps)
        logp = jax.nn.log_softmax(
            (hidden @ w["head"]).astype(jnp.float32),
            axis=-1).reshape(B, K, V)
        if eos_id is not None:
            # finished beams may only extend with eos at unchanged score
            frozen = jnp.full((V,), NEG).at[eos_id].set(0.0)
            logp = jnp.where(done[..., None], frozen[None, None, :], logp)
        total = scores[..., None] + logp                      # [B, K, V]
        flat = total.reshape(B, K * V)
        new_scores, idx = jax.lax.top_k(flat, K)              # [B, K]
        beam_idx = idx // V
        new_tok = (idx % V).astype(jnp.int32)

        # reorder beam state
        gidx = (jnp.arange(B)[:, None] * K + beam_idx).reshape(B * K)
        kcache = kcache[:, gidx]
        vcache = vcache[:, gidx]
        toks = jnp.take_along_axis(toks, beam_idx[..., None], axis=1)
        done = jnp.take_along_axis(done, beam_idx, axis=1)
        toks = jax.lax.dynamic_update_index_in_dim(
            toks, new_tok, i, 2)
        if eos_id is not None:
            done = jnp.logical_or(done, new_tok == eos_id)
        return (toks, new_scores, cur_pos + 1, kcache, vcache, done), None

    if max_new > 1:
        carry = (toks, scores, jnp.int32(L0), kcache, vcache, done)
        carry, _ = jax.lax.scan(decode_step, carry,
                                jnp.arange(1, max_new))
        toks, scores, _, _, _, done = carry

    # length penalty on the final ranking (HF BeamSearchScorer)
    if eos_id is not None:
        lengths = jnp.argmax(
            jnp.concatenate([toks == eos_id,
                             jnp.ones((B, K, 1), bool)], axis=2),
            axis=2) + 1
    else:
        lengths = jnp.full((B, K), max_new)
    ranked = scores / (lengths.astype(jnp.float32) ** length_penalty)
    best = jnp.argmax(ranked, axis=1)
    best_toks = jnp.take_along_axis(
        toks, best[:, None, None].repeat(max_new, 2), axis=1)[:, 0]
    return jnp.concatenate([input_ids, best_toks], axis=1)


def beam_search_generate(model, input_ids, max_new_tokens: int = 32,
                         num_beams: int = 4,
                         eos_token_id: Optional[int] = None,
                         length_penalty: float = 1.0):
    """Beam search for LlamaForCausalLM (HF/PaddleNLP beam semantics)."""
    c = model.config
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(
        input_ids)
    w = _stacked_weights(model)
    out = _beam_generate_jit(
        w, ids.astype(jnp.int32), n_heads=c.num_attention_heads,
        n_kv=c.num_key_value_heads, eps=c.rms_norm_eps, theta=c.rope_theta,
        max_new=int(max_new_tokens), num_beams=int(num_beams),
        eos_id=eos_token_id, length_penalty=jnp.float32(length_penalty))
    return Tensor(out)


# ---------------------------------------------------------------------------
# GPT (pre-LN, learned positions, combined qkv)
# ---------------------------------------------------------------------------

def _gpt_stacked_weights(model):
    blocks = model.gpt.blocks

    def st(get):
        return jnp.stack([get(b) for b in blocks])

    w = {
        "wqkv": st(lambda b: b.qkv.weight._data),
        "bqkv": st(lambda b: b.qkv.bias._data),
        "wproj": st(lambda b: b.proj.weight._data),
        "bproj": st(lambda b: b.proj.bias._data),
        "ln1w": st(lambda b: b.ln_1.weight._data),
        "ln1b": st(lambda b: b.ln_1.bias._data),
        "ln2w": st(lambda b: b.ln_2.weight._data),
        "ln2b": st(lambda b: b.ln_2.bias._data),
        "wfc1": st(lambda b: b.fc1.weight._data),
        "bfc1": st(lambda b: b.fc1.bias._data),
        "wfc2": st(lambda b: b.fc2.weight._data),
        "bfc2": st(lambda b: b.fc2.bias._data),
    }
    w["wte"] = model.gpt.wte.weight._data
    w["wpe"] = model.gpt.wpe.weight._data
    w["lnfw"] = model.gpt.ln_f.weight._data
    w["lnfb"] = model.gpt.ln_f.bias._data
    w["head"] = model.lm_head.weight._data
    return w


def _ln(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    v = jnp.var(xf, axis=-1, keepdims=True)
    return (((xf - m) * jax.lax.rsqrt(v + eps)).astype(x.dtype) * w + b)


_GPT_STACK_KEYS = ("wqkv", "bqkv", "wproj", "bproj", "ln1w", "ln1b", "ln2w",
                   "ln2b", "wfc1", "bfc1", "wfc2", "bfc2")


def _gpt_prefill_layer(x, lw, *, n_heads):
    """One GPT block over a full [B, L] prompt (causal; positions enter
    via the wpe embedding). Returns (x, (k, v)), k/v [B, L, H, hd]."""
    B, L, h = x.shape
    hd = h // n_heads
    dt = x.dtype
    hN = _ln(x, lw["ln1w"], lw["ln1b"])
    qkv = hN @ lw["wqkv"] + lw["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, L, n_heads, hd)
    k = k.reshape(B, L, n_heads, hd)
    v = v.reshape(B, L, n_heads, hd)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    cm = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(cm, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = jnp.swapaxes(o, 1, 2).reshape(B, L, h)
    x = x + o @ lw["wproj"] + lw["bproj"]
    h2 = _ln(x, lw["ln2w"], lw["ln2b"])
    x = x + jax.nn.gelu(h2 @ lw["wfc1"] + lw["bfc1"],
                        approximate=False) @ lw["wfc2"] + lw["bfc2"]
    return x, (k, v)


def _gpt_decode_layer(xt, lw, kc_l, vc_l, write_idx, key_mask, *, n_heads):
    """One GPT block advancing every row one token (learned positions are
    applied at the embedding, so only the cache line index matters here).
    kc_l/vc_l [B, T, H, hd]; write_idx [B]; key_mask as in the Llama
    decode layer."""
    B, T = kc_l.shape[0], kc_l.shape[1]
    h = xt.shape[-1]
    hd = h // n_heads
    dt = xt.dtype
    hN = _ln(xt, lw["ln1w"], lw["ln1b"])
    qkv = hN @ lw["wqkv"] + lw["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, 1, n_heads, hd)
    k = k.reshape(B, 1, n_heads, hd)
    v = v.reshape(B, 1, n_heads, hd)
    rows = jnp.arange(B)
    kc_l = kc_l.at[rows, write_idx].set(k[:, 0])
    vc_l = vc_l.at[rows, write_idx].set(v[:, 0])
    s = jnp.einsum("bhd,bthd->bht", q[:, 0], kc_l,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(hd))
    valid = jnp.arange(T)[None, :] <= write_idx[:, None]
    if key_mask is not None:
        valid = jnp.logical_and(valid, key_mask)
    s = jnp.where(valid[:, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    o = jnp.einsum("bht,bthd->bhd", p, vc_l).reshape(B, 1, h)
    xt2 = xt + o @ lw["wproj"] + lw["bproj"]
    h2 = _ln(xt2, lw["ln2w"], lw["ln2b"])
    xt2 = xt2 + jax.nn.gelu(h2 @ lw["wfc1"] + lw["bfc1"],
                            approximate=False) @ lw["wfc2"] + lw["bfc2"]
    return xt2, kc_l, vc_l


@functools.partial(jax.jit, static_argnames=(
    "n_heads", "max_new", "do_sample", "top_k", "eos_id", "top_p",
    "padded"))
def _gpt_generate_jit(w, input_ids, prompt_len_mask, key, *, n_heads,
                      max_new, do_sample, top_k, eos_id, temperature,
                      top_p=None, padded=False):
    B, L0 = input_ids.shape
    h = w["wte"].shape[1]
    hd = h // n_heads
    T = L0 + max_new
    dt = w["wte"].dtype

    pos = jnp.arange(L0)
    x = jnp.take(w["wte"], input_ids, axis=0) + w["wpe"][pos][None]
    kcache = jnp.zeros((w["wqkv"].shape[0], B, T, n_heads, hd), dt)
    vcache = jnp.zeros_like(kcache)

    stack = {k: w[k] for k in _GPT_STACK_KEYS}

    def one_prefill(x, lw):
        return _gpt_prefill_layer(x, lw, n_heads=n_heads)

    x, kvs = jax.lax.scan(one_prefill, x, stack)
    kcache = kcache.at[:, :, :L0].set(kvs[0])
    vcache = vcache.at[:, :, :L0].set(kvs[1])

    prompt_len = jnp.sum(prompt_len_mask, axis=1).astype(jnp.int32)
    last_idx = prompt_len - 1
    xlast = jnp.take_along_axis(
        x, last_idx[:, None, None].repeat(h, 2), axis=1)[:, 0]
    logits0 = _ln(xlast, w["lnfw"], w["lnfb"]) @ w["head"]

    def sample(logits, key):
        logits = _filter_logits(logits, temperature, do_sample, top_k, top_p)
        if not do_sample:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    key, sk = jax.random.split(key)
    tok0 = sample(logits0, sk)
    out = jnp.zeros((B, max_new), jnp.int32).at[:, 0].set(tok0)
    done0 = (tok0 == eos_id) if eos_id is not None else jnp.zeros((B,), bool)

    key_mask = (jnp.concatenate(
        [prompt_len_mask.astype(bool), jnp.ones((B, max_new), bool)],
        axis=1) if padded else None)

    def decode_step(carry, i):
        tok, cur_pos, kcache, vcache, key, done = carry
        write_idx = jnp.full((B,), cur_pos, jnp.int32)
        rope_pos = prompt_len + (i - 1) if padded else write_idx
        xt = (jnp.take(w["wte"], tok, axis=0)
              + jnp.take(w["wpe"], rope_pos, axis=0))[:, None]

        def one(cx, lw_kv):
            xt2, kc_l, vc_l = _gpt_decode_layer(
                cx["x"], lw_kv, lw_kv["kc"], lw_kv["vc"], write_idx,
                key_mask, n_heads=n_heads)
            return {"x": xt2}, (kc_l, vc_l)

        lw_kv = dict(stack)
        lw_kv["kc"] = kcache
        lw_kv["vc"] = vcache
        cx, (kcache, vcache) = jax.lax.scan(one, {"x": xt}, lw_kv)
        logits = _ln(cx["x"][:, 0], w["lnfw"], w["lnfb"]) @ w["head"]
        key, sk = jax.random.split(key)
        nxt = sample(logits, sk)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = jnp.logical_or(done, nxt == eos_id)
        return (nxt, cur_pos + 1, kcache, vcache, key, done), nxt

    if max_new > 1:
        carry = (tok0, jnp.int32(L0), kcache, vcache, key, done0)
        _, toks = jax.lax.scan(decode_step, carry, jnp.arange(1, max_new))
        out = out.at[:, 1:].set(jnp.swapaxes(toks, 0, 1))
    return jnp.concatenate([input_ids, out], axis=1)


def gpt_generate(model, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, top_k: int = 0,
                 temperature: float = 1.0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 top_p: Optional[float] = None,
                 pad_token_id: Optional[int] = None, attention_mask=None):
    """Greedy / top-k generation for GPTForCausalLM (same static-cache
    design as the Llama path). Right-padded prompts are supported via
    pad_token_id and/or an explicit attention_mask, as in generate()."""
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(
        input_ids)
    ids = ids.astype(jnp.int32)
    mask = _prompt_mask(ids, pad_token_id, attention_mask)
    padded = pad_token_id is not None or attention_mask is not None
    w = _gpt_stacked_weights(model)
    out = _gpt_generate_jit(
        w, ids, mask, jax.random.PRNGKey(seed),
        n_heads=model.config.num_attention_heads,
        max_new=int(max_new_tokens), do_sample=bool(do_sample),
        top_k=int(top_k), eos_id=eos_token_id,
        temperature=jnp.float32(temperature),
        top_p=None if top_p is None else float(top_p), padded=padded)
    return Tensor(out)


def generate(model, input_ids, max_new_tokens: int = 32,
             do_sample: bool = False, top_k: int = 0,
             temperature: float = 1.0,
             eos_token_id: Optional[int] = None, seed: int = 0,
             top_p: Optional[float] = None,
             pad_token_id: Optional[int] = None, attention_mask=None):
    """Greedy / top-k sampled generation for LlamaForCausalLM.

    input_ids: Tensor [B, L0]. Right-padded prompts are supported: pass
    pad_token_id (mask derived from trailing pad tokens) and/or an
    explicit attention_mask [B, L0]; pad positions are excluded from
    attention and each row's generated tokens take rotary positions
    continuing from its own prompt length. Without either, every token
    is treated as real context. Returns Tensor [B, L0 + max_new_tokens].
    """
    c = model.config
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(
        input_ids)
    ids = ids.astype(jnp.int32)
    mask = _prompt_mask(ids, pad_token_id, attention_mask)
    padded = pad_token_id is not None or attention_mask is not None
    w = _stacked_weights(model)
    key = jax.random.PRNGKey(seed)
    out = _generate_jit(
        w, ids, mask, key, n_heads=c.num_attention_heads,
        n_kv=c.num_key_value_heads, eps=c.rms_norm_eps, theta=c.rope_theta,
        max_new=int(max_new_tokens), do_sample=bool(do_sample),
        top_k=int(top_k), eos_id=eos_token_id,
        temperature=jnp.float32(temperature),
        top_p=None if top_p is None else float(top_p), padded=padded)
    return Tensor(out)
