"""Top-level extras, new functionals/layers, distribution additions, fft
hermitian transforms, beam search, worker info."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


class TestTensorExtras:
    def test_add_n_mv_sgn(self):
        a = paddle.to_tensor(np.ones((2, 2), dtype=np.float32))
        s = paddle.add_n([a, a, a])
        np.testing.assert_allclose(np.asarray(s._data), 3 * np.ones((2, 2)))
        m = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        v = paddle.to_tensor(np.ones(3, dtype=np.float32))
        np.testing.assert_allclose(np.asarray(paddle.mv(m, v)._data),
                                   [3., 12.])
        np.testing.assert_allclose(
            np.asarray(paddle.sgn(paddle.to_tensor([-3., 0., 5.]))._data),
            [-1., 0., 1.])

    def test_logcumsumexp_matches_numpy(self):
        x = np.random.default_rng(0).normal(size=(10,)).astype(np.float32)
        got = np.asarray(paddle.logcumsumexp(paddle.to_tensor(x))._data)
        ref = np.log(np.cumsum(np.exp(x)))
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_inplace_variants_keep_grad(self):
        x = paddle.to_tensor(np.ones((4,), dtype=np.float32),
                             stop_gradient=False)
        y = (x * 2.0)
        paddle.tanh_(y)
        y.sum().backward()
        ref = 2.0 / np.cosh(2.0) ** 2
        np.testing.assert_allclose(np.asarray(x.grad._data),
                                   np.full(4, ref), atol=1e-6)

    def test_shape_rank_tolist_reverse(self):
        t = paddle.to_tensor(np.arange(6).reshape(2, 3))
        assert np.asarray(paddle.shape(t)._data).tolist() == [2, 3]
        assert int(paddle.rank(t)._data) == 2
        assert paddle.tolist(t) == [[0, 1, 2], [3, 4, 5]]
        r = paddle.reverse(paddle.to_tensor([1., 2., 3.]), axis=0)
        np.testing.assert_allclose(np.asarray(r._data), [3., 2., 1.])


class TestPoolingMaskUnpool:
    def test_mask_is_argmax_flat_index(self):
        x = np.zeros((1, 1, 4, 4), dtype=np.float32)
        x[0, 0, 1, 2] = 9.0  # flat index 6 within its 2x2 window region
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, 2,
                                 return_mask=True)
        assert np.asarray(out._data)[0, 0, 0, 1] == 9.0
        assert np.asarray(mask._data)[0, 0, 0, 1] == 1 * 4 + 2

    def test_unpool_roundtrip_2d_3d_1d(self):
        rng = np.random.default_rng(0)
        x2 = paddle.to_tensor(
            rng.normal(size=(2, 3, 8, 8)).astype(np.float32))
        o, m = F.max_pool2d(x2, 2, 2, return_mask=True)
        r = F.max_unpool2d(o, m, 2, 2)
        assert list(r.shape) == [2, 3, 8, 8]
        np.testing.assert_allclose(np.asarray(r._data).sum(),
                                   np.asarray(o._data).sum(), rtol=1e-5)
        x1 = paddle.to_tensor(rng.normal(size=(2, 3, 8)).astype(np.float32))
        o1, m1 = F.max_pool1d(x1, 2, 2, return_mask=True)
        assert list(F.max_unpool1d(o1, m1, 2, 2).shape) == [2, 3, 8]
        x3 = paddle.to_tensor(
            rng.normal(size=(1, 2, 4, 4, 4)).astype(np.float32))
        o3, m3 = F.max_pool3d(x3, 2, 2, return_mask=True)
        assert list(F.max_unpool3d(o3, m3, 2, 2).shape) == [1, 2, 4, 4, 4]

    def test_unpool_layer(self):
        x = paddle.to_tensor(np.random.default_rng(1)
                             .normal(size=(1, 2, 6, 6)).astype(np.float32))
        o, m = F.max_pool2d(x, 2, 2, return_mask=True)
        layer = nn.MaxUnPool2D(2, 2)
        assert list(layer(o, m).shape) == [1, 2, 6, 6]


class TestNewLosses:
    def test_dice_loss_perfect_prediction_near_zero(self):
        label = paddle.to_tensor(np.array([[0], [1], [2]]))
        probs = paddle.to_tensor(np.eye(3, dtype=np.float32))
        loss = F.dice_loss(probs, label)
        assert float(loss._data) < 1e-4

    def test_soft_margin_matches_formula(self):
        x = np.array([[0.5, -1.0]], dtype=np.float32)
        y = np.array([[1.0, -1.0]], dtype=np.float32)
        got = float(F.soft_margin_loss(paddle.to_tensor(x),
                                       paddle.to_tensor(y))._data)
        ref = np.mean(np.log1p(np.exp(-y * x)))
        assert abs(got - ref) < 1e-6

    def test_hsigmoid_loss_decreases_with_training(self):
        paddle.seed(0)
        from paddle_tpu import optimizer as optim
        layer = nn.HSigmoidLoss(8, 6)
        feats = paddle.to_tensor(np.random.default_rng(0)
                                 .normal(size=(32, 8)).astype(np.float32))
        labels = paddle.to_tensor(
            np.random.default_rng(1).integers(0, 6, (32,)))
        opt = optim.Adam(learning_rate=5e-2,
                         parameters=layer.parameters())
        first = None
        for _ in range(30):
            loss = layer(feats, labels).mean()
            if first is None:
                first = float(loss._data)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss._data) < first * 0.7

    def test_margin_cross_entropy_zero_margin_is_softmax_ce(self):
        rng = np.random.default_rng(2)
        cos = rng.uniform(-1, 1, (4, 6)).astype(np.float32)
        y = rng.integers(0, 6, (4,))
        got = float(F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(y), margin1=1.0,
            margin2=0.0, margin3=0.0, scale=1.0)._data)
        z = cos - cos.max(-1, keepdims=True)
        logp = z - np.log(np.exp(z).sum(-1, keepdims=True))
        ref = -logp[np.arange(4), y].mean()
        assert abs(got - ref) < 1e-5

    def test_sigmoid_focal_loss_gamma0_is_weighted_bce(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(5,)).astype(np.float32)
        y = (rng.random(5) > 0.5).astype(np.float32)
        got = float(F.sigmoid_focal_loss(
            paddle.to_tensor(x), paddle.to_tensor(y), alpha=0.5,
            gamma=0.0, reduction='sum')._data)
        ce = np.maximum(x, 0) - x * y + np.log1p(np.exp(-np.abs(x)))
        assert abs(got - 0.5 * ce.sum()) < 1e-5


class TestExtension:
    def test_sequence_mask_diag_embed(self):
        m = F.sequence_mask(paddle.to_tensor(np.array([2, 0, 3])), maxlen=3)
        np.testing.assert_array_equal(
            np.asarray(m._data), [[1, 1, 0], [0, 0, 0], [1, 1, 1]])
        d = F.diag_embed(paddle.to_tensor(np.array([1., 2.],
                                                   dtype=np.float32)))
        np.testing.assert_allclose(np.asarray(d._data),
                                   [[1., 0.], [0., 2.]])
        off = F.diag_embed(paddle.to_tensor(
            np.array([1.], dtype=np.float32)), offset=1)
        assert np.asarray(off._data)[0, 1] == 1.0

    def test_temporal_shift_moves_channels(self):
        x = np.zeros((4, 4, 1, 1), dtype=np.float32)  # N*T=4 (T=2), C=4
        x[0, 0] = 1.0  # clip 0, time 0, channel 0
        out = np.asarray(F.temporal_shift(
            paddle.to_tensor(x), seg_num=2, shift_ratio=0.25)._data)
        # channel 0 shifts backward: value from t=1 lands at t=0 → zeroed
        assert out[0, 0] == 0.0

    def test_class_center_sample(self):
        y = paddle.to_tensor(np.array([3, 7, 3]))
        remapped, sampled = F.class_center_sample(y, 20, 5)
        s = np.asarray(sampled._data)
        r = np.asarray(remapped._data)
        assert len(s) == 5 and 3 in s and 7 in s
        assert (s[r] == np.array([3, 7, 3])).all()


class TestDistributionExtras:
    def test_multinomial_mean_logprob(self):
        p = np.array([0.2, 0.3, 0.5], dtype=np.float32)
        d = paddle.distribution.Multinomial(10, paddle.to_tensor(p))
        np.testing.assert_allclose(np.asarray(d.mean._data), 10 * p,
                                   rtol=1e-6)
        counts = paddle.to_tensor(np.array([2., 3., 5.], dtype=np.float32))
        from scipy import stats  # scipy is available via jax dependency
        ref = stats.multinomial.logpmf([2, 3, 5], 10, p)
        assert abs(float(d.log_prob(counts)._data) - ref) < 1e-4
        s = d.sample((7,))
        assert np.asarray(s._data).sum(-1).tolist() == [10.0] * 7

    def test_independent_sums_event_dims(self):
        base = paddle.distribution.Normal(
            paddle.to_tensor(np.zeros((2, 3), dtype=np.float32)),
            paddle.to_tensor(np.ones((2, 3), dtype=np.float32)))
        ind = paddle.distribution.Independent(base, 1)
        v = paddle.to_tensor(np.zeros((2, 3), dtype=np.float32))
        lp = ind.log_prob(v)
        assert list(lp.shape) == [2]
        np.testing.assert_allclose(np.asarray(lp._data),
                                   3 * -0.5 * np.log(2 * np.pi), rtol=1e-5)

    def test_transformed_distribution_lognormal(self):
        base = paddle.distribution.Normal(0.0, 1.0)
        d = paddle.distribution.TransformedDistribution(
            base, [paddle.distribution.ExpTransform()])
        v = paddle.to_tensor(np.array(2.0, dtype=np.float32))
        got = float(d.log_prob(v)._data)
        from scipy import stats
        assert abs(got - stats.lognorm.logpdf(2.0, 1.0)) < 1e-5

    def test_register_kl(self):
        from paddle_tpu.distribution import (Bernoulli, kl_divergence,
                                             register_kl)

        @register_kl(Bernoulli, Bernoulli)
        def _kl_bb(p, q):
            return paddle.to_tensor(np.float32(0.125))

        out = kl_divergence(Bernoulli(0.3), Bernoulli(0.7))
        assert float(out._data) == 0.125


class TestFFTHermitian:
    def test_hfft2_roundtrip(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 5)).astype(np.float32)
        spec = paddle.fft.ihfft2(paddle.to_tensor(x))
        back = paddle.fft.hfft2(spec, s=x.shape)
        np.testing.assert_allclose(np.asarray(back._data), x, atol=1e-4)

    def test_hfftn_matches_numpy_last_axis(self):
        rng = np.random.default_rng(1)
        x = (rng.normal(size=(6,)) + 1j * rng.normal(size=(6,)))
        x[0] = x[0].real  # hermitian-compatible DC
        got = np.asarray(paddle.fft.hfftn(
            paddle.to_tensor(x.astype(np.complex64)), axes=(0,))._data)
        ref = np.fft.hfft(x)
        np.testing.assert_allclose(got, ref, atol=1e-3)


class TestBeamSearch:
    def test_beam_search_finds_best_path(self):
        """A cell emitting FIXED per-step logits: beam search must find the
        argmax-sum token sequence that brute force finds."""
        vocab, steps, beam = 5, 3, 4

        rng = np.random.default_rng(0)
        step_logits = rng.normal(size=(steps, vocab)).astype(np.float32)
        step_logits[:, 1] -= 100.0  # token 1 = end token, keep alive

        class FixedCell(nn.Layer):
            def forward(self, inputs, states):
                t = int(np.asarray(states._data).flat[0])
                batch = inputs.shape[0]
                logits = np.tile(step_logits[min(t, steps - 1)],
                                 (batch, 1))
                return (paddle.to_tensor(logits),
                        paddle.to_tensor(
                            np.asarray(states._data) + 1))

        dec = nn.BeamSearchDecoder(FixedCell(), start_token=0, end_token=1,
                                   beam_size=beam)
        init_state = paddle.to_tensor(np.zeros((2, 1), dtype=np.float32))
        out, _ = nn.dynamic_decode(dec, inits=init_state,
                                   max_step_num=steps)
        preds = np.asarray(out.predicted_ids._data)  # [B, T, beam]
        # brute force best token per step (greedy == optimal: per-step
        # independent logits)
        best = step_logits.argmax(-1)
        np.testing.assert_array_equal(preds[0, :, 0], best)
        np.testing.assert_array_equal(preds[1, :, 0], best)
        # beams are score-sorted: beam 0 total >= beam 1 total
        scores = np.asarray(out.scores._data)
        assert scores[0, -1, 0] >= scores[0, -1, 1]

    def test_beam_search_stops_at_end_token(self):
        vocab = 4

        class EndCell(nn.Layer):
            def forward(self, inputs, states):
                batch = inputs.shape[0]
                logits = np.full((batch, vocab), -5.0, dtype=np.float32)
                logits[:, 2] = 5.0  # always pick end token 2
                return paddle.to_tensor(logits), states

        dec = nn.BeamSearchDecoder(EndCell(), start_token=0, end_token=2,
                                   beam_size=2)
        init = paddle.to_tensor(np.zeros((1, 1), dtype=np.float32))
        out, states, lengths = nn.dynamic_decode(
            dec, inits=init, max_step_num=50, return_length=True)
        # beam 0 finishes at step 1; beam 1 (forked survivor) by step 2 —
        # far before max_step_num
        assert np.asarray(out.predicted_ids._data).shape[1] <= 2
        assert np.asarray(lengths._data).max() <= 2
        assert np.asarray(out.predicted_ids._data)[0, 0, 0] == 2


class TestWorkerInfo:
    def test_get_worker_info_inside_worker(self):
        seen = []

        class Probe(paddle.io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                info = paddle.io.get_worker_info()
                seen.append(None if info is None else info.num_workers)
                return np.float32(i)

        dl = paddle.io.DataLoader(Probe(), batch_size=4, num_workers=2)
        list(dl)
        assert any(s == 2 for s in seen)
        assert paddle.io.get_worker_info() is None


def test_get_value_set_value_roundtrip():
    import numpy as np

    x = paddle.to_tensor([1.0, 2.0])
    v = x.get_value()
    assert np.allclose(v.numpy(), x.numpy())
    x.set_value(np.array([3.0, 4.0], np.float32))
    assert float(x.numpy()[0]) == 3.0
    p = paddle.nn.Linear(2, 2).weight
    p.set_value(p.get_value())


def test_save_load_file_like():
    import io as _io

    import numpy as np

    buf = _io.BytesIO()
    paddle.save(paddle.to_tensor([1.0, 2.0]), buf)
    buf.seek(0)
    t = paddle.load(buf)
    assert np.allclose(t.numpy(), [1.0, 2.0])


def test_program_state_dict_roundtrip():
    import numpy as np

    import paddle_tpu.static as static

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4])
        w = static.create_parameter([4, 2], "float32", name="w0")
    sd = main.state_dict("param")
    assert "w0" in sd
    new_w = np.ones((4, 2), np.float32)
    missing = main.set_state_dict({"w0": new_w, "nope": new_w})
    assert missing == ["nope"]
    assert np.allclose(np.asarray(main.var("w0")._data), 1.0)
