"""Reference spelling: python/paddle/nn/decode.py (seq2seq decoding API).

Implementations live in nn/layer/decode.py (lax.while_loop-based
dynamic_decode with a static-shape step state — see that module's
docstring for the TPU design).
"""
from .layer.decode import BeamSearchDecoder, Decoder, dynamic_decode

__all__ = ["BeamSearchDecoder", "Decoder", "dynamic_decode"]
