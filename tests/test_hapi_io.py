"""hapi Model fit/evaluate/predict, DataLoader, save/load, jit.save/load."""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.io import DataLoader, Dataset, TensorDataset, random_split


class _SynthDataset(Dataset):
    def __init__(self, n=64):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(n, 8)).astype(np.float32)
        self.w = rng.normal(size=(8,)).astype(np.float32)
        self.y = (self.x @ self.w > 0).astype(np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _mlp():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))


def test_hapi_fit_evaluate_predict():
    ds = _SynthDataset()
    model = paddle.Model(_mlp())
    model.prepare(
        optim.Adam(learning_rate=5e-2,
                   parameters=model.network.parameters()),
        nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    model.fit(ds, epochs=4, batch_size=16, verbose=0)
    res = model.evaluate(ds, batch_size=16, verbose=0)
    assert res["acc"] > 0.8, f"did not learn: {res}"
    preds = model.predict(ds, batch_size=16, verbose=0)
    stacked = np.concatenate([np.asarray(p._data) for p in preds])
    assert stacked.shape == (64, 2)


def test_hapi_callbacks_checkpoint():
    from paddle_tpu.hapi.callbacks import EarlyStopping, ModelCheckpoint

    ds = _SynthDataset()
    with tempfile.TemporaryDirectory() as td:
        model = paddle.Model(_mlp())
        model.prepare(
            optim.Adam(learning_rate=5e-2,
                       parameters=model.network.parameters()),
            nn.CrossEntropyLoss(), paddle.metric.Accuracy())
        model.fit(ds, epochs=2, batch_size=16, verbose=0,
                  callbacks=[ModelCheckpoint(save_dir=td, save_freq=1)])
        assert any(f.endswith(".pdparams") for root, _, fs in os.walk(td)
                   for f in fs), "no checkpoint written"


def test_dataloader_shuffle_and_split():
    ds = _SynthDataset(60)
    train, val = random_split(ds, [48, 12])
    assert len(train) == 48 and len(val) == 12
    dl = DataLoader(train, batch_size=16, shuffle=True, drop_last=True)
    batches = list(dl)
    assert len(batches) == 3
    xb, yb = batches[0]
    assert list(xb.shape) == [16, 8]


def test_tensor_dataset_and_workers():
    x = np.arange(40 * 4, dtype=np.float32).reshape(40, 4)
    y = np.arange(40, dtype=np.int64)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    dl = DataLoader(ds, batch_size=10, num_workers=2)
    total = sum(int(b[1].shape[0]) for b in dl)
    assert total == 40


def test_save_load_optimizer_state():
    model = _mlp()
    opt = optim.Adam(learning_rate=1e-3, parameters=model.parameters())
    x = paddle.to_tensor(np.ones((4, 8), dtype=np.float32))
    loss = model(x).sum()
    loss.backward()
    opt.step()
    with tempfile.TemporaryDirectory() as td:
        mp = os.path.join(td, "m.pdparams")
        op = os.path.join(td, "o.pdopt")
        paddle.save(model.state_dict(), mp)
        paddle.save(opt.state_dict(), op)
        m2 = _mlp()
        o2 = optim.Adam(learning_rate=1e-3, parameters=m2.parameters())
        m2.set_state_dict(paddle.load(mp))
        o2.set_state_dict(paddle.load(op))
        for (k1, v1), (k2, v2) in zip(sorted(model.state_dict().items()),
                                      sorted(m2.state_dict().items())):
            assert k1 == k2
            np.testing.assert_array_equal(np.asarray(v1._data),
                                          np.asarray(v2._data))


def test_jit_save_load_roundtrip():
    from paddle_tpu import jit
    from paddle_tpu.static import InputSpec

    layer = _mlp()
    layer.eval()
    x = paddle.to_tensor(
        np.random.default_rng(1).normal(size=(2, 8)).astype(np.float32))
    ref = layer(x).numpy()
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "model")
        jit.save(layer, path, input_spec=[InputSpec([None, 8], "float32")])
        loaded = jit.load(path)
        out = loaded(x)
        out = out.numpy() if hasattr(out, "numpy") else np.asarray(out)
        np.testing.assert_allclose(out, ref, atol=1e-5)
