"""Eager tape vs jax.grad oracle (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def test_backward_simple():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = paddle.sum(x * x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_backward_chain():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    w = paddle.to_tensor([[0.5, -0.5], [1.0, 2.0]], stop_gradient=False)
    out = paddle.matmul(x, w)
    loss = paddle.mean(paddle.tanh(out) ** 2)

    def oracle(xr, wr):
        return jnp.mean(jnp.tanh(xr @ wr) ** 2)

    gx, gw = jax.grad(oracle, argnums=(0, 1))(x.numpy(), w.numpy())
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), gx, rtol=1e-5)
    np.testing.assert_allclose(w.grad.numpy(), gw, rtol=1e-5)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    loss = paddle.sum(x * y)
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_no_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y._node is None and y.stop_gradient


def test_detach():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = paddle.sum(y * x)
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])


def test_multi_output_op():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b, c = paddle.split(x, 3, axis=1)
    loss = paddle.sum(a * 1 + b * 2 + c * 3)
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[1, 2, 3], [1, 2, 3]])


def test_branching_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    a = x * 3
    b = x * 4
    loss = paddle.sum(a * b)  # d/dx(12 x^2) = 24x = 48
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [48.0])


def test_functional_grad():
    def f(x):
        return paddle.sum(paddle.sin(x) * x)

    g = paddle.grad(f)(paddle.to_tensor([1.0, 2.0]))
    expected = np.sin([1.0, 2.0]) + np.asarray([1.0, 2.0]) * np.cos([1.0, 2.0])
    np.testing.assert_allclose(g.numpy(), expected, rtol=1e-5)


def test_py_layer():
    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return dy * 3 * x * x

    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = Cube.apply(x)
    paddle.sum(y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_backward_nonscalar_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * x
    y.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 40.0])


def test_second_order_via_functional():
    def f(x):
        return paddle.sum(x ** 3)

    h = paddle.autograd.hessian(f, paddle.to_tensor([1.0, 2.0]))
    np.testing.assert_allclose(np.diag(h.numpy()), [6.0, 12.0], rtol=1e-5)


def test_incubate_autograd_jacobian_hessian_forward_grad():
    """Reference: python/paddle/incubate/autograd/__init__.py surface
    (Jacobian, Hessian, forward_grad, enable_prim)."""
    import numpy as np

    from paddle_tpu.incubate import autograd as A

    x = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
    J = A.Jacobian(lambda t: t ** 2, x)
    np.testing.assert_allclose(J[:].numpy(),
                               np.diag([2.0, 4.0]), atol=1e-6)
    assert J.shape == (2, 2)
    np.testing.assert_allclose(J[0].numpy(), [2.0, 0.0], atol=1e-6)

    H = A.Hessian(lambda t: (t ** 3).sum(), x)
    np.testing.assert_allclose(H[:].numpy(),
                               np.diag([6.0, 12.0]), atol=1e-6)

    tangent = A.forward_grad(
        lambda t: t ** 2, x,
        paddle.to_tensor(np.asarray([1.0, 0.0], np.float32)))
    np.testing.assert_allclose(tangent.numpy(), [2.0, 0.0], atol=1e-6)

    A.enable_prim()
    assert A.prim_enabled()
    A.disable_prim()
    assert not A.prim_enabled()
