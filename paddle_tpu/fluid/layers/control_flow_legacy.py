"""Block-style legacy control flow: While / IfElse / Switch.

Reference: python/paddle/fluid/layers/control_flow.py (While:1100,
IfElse:1751, Switch:2395). The reference appends sub-block ops to the
program and the C++ executor loops/branches over them; here the
define-by-run Program records each block as an op span and collapses it
into a single thunk that re-replays the span — so data-dependent loop
conditions work at Executor.run time (each iteration re-executes the
recorded body eagerly).

Lax-backed `cond`/`while_loop` (static/nn.py) remain the compiled,
jit-friendly path; these classes exist for 1.x-era scripts.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ...static.program import Program, default_main_program


def _scalar_bool(t):
    return bool(np.asarray(t._data).reshape(-1)[0])


@contextlib.contextmanager
def _captured_span(prog):
    """Record ops into prog, then pop them off as a span on exit."""
    start = len(prog._ops)
    holder = {}
    try:
        yield holder
    finally:
        holder["span"] = prog._ops[start:]
        del prog._ops[start:]


class While:
    """``while_op = While(cond); with while_op.block(): ...`` — the body
    must refresh ``cond`` (e.g. ``less_than(i, n, cond=cond)``).
    Reference: fluid/layers/control_flow.py:While."""

    def __init__(self, cond, is_test=False, name=None):
        self._cond = cond
        self._prog = default_main_program()

    @contextlib.contextmanager
    def block(self):
        with _captured_span(self._prog) as holder:
            yield
        span = holder["span"]
        cond = self._cond

        def _loop():
            guard = 0
            while _scalar_bool(cond):
                Program._replay_entries(span)
                guard += 1
                if guard > 10_000_000:
                    raise RuntimeError("While exceeded 1e7 iterations")

        # structured entry: the jitted Executor lowers this block to one
        # lax.while_loop (carry = cond + every tensor the span writes);
        # _loop stays the eager fallback at entry[1]
        self._prog._ops.append(("while", _loop, cond, span))


class IfElse:
    """Row-wise conditional (reference fluid/layers/control_flow.py:
    IfElse): rows of the inputs where ``cond`` holds flow through the
    true block, the rest through the false block, and ``()`` merges the
    outputs back in row order.

    TPU-dense semantics: both blocks run on the FULL batch and the merge
    selects rows by ``cond`` — same results, no gather/scatter of
    dynamic row subsets (which would be unshardable shapes).
    """

    def __init__(self, cond, name=None):
        self._cond = cond
        self._prog = default_main_program()
        self._outputs = {True: [], False: []}
        self._in_true = None

    def input(self, x):
        return x  # full batch; the merge applies the row mask

    @contextlib.contextmanager
    def true_block(self):
        # block ops record (and replay) unconditionally — the merge in
        # __call__ row-selects; the context only routes output()
        self._in_true = True
        try:
            yield
        finally:
            self._in_true = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_true = False
        try:
            yield
        finally:
            self._in_true = None

    def output(self, *outs):
        if self._in_true is None:
            raise RuntimeError("IfElse.output called outside a block")
        self._outputs[self._in_true].extend(outs)

    def __call__(self):
        from ...tensor_ops.manipulation import where as _where
        true_outs = self._outputs[True]
        false_outs = self._outputs[False]
        if len(true_outs) != len(false_outs):
            raise ValueError(
                "IfElse true/false blocks produced different output "
                f"counts: {len(true_outs)} vs {len(false_outs)}")
        from ...tensor_ops.manipulation import squeeze, unsqueeze
        merged = []
        for t, f in zip(true_outs, false_outs):
            # align cond's rank to the output: pad with trailing 1-dims
            # for higher-rank outputs, squeeze trailing 1-dims for
            # lower-rank ones ([N,1] cond vs [N] output must not
            # broadcast to [N,N])
            c = self._cond
            while len(c.shape) < len(t.shape):
                c = unsqueeze(c, axis=-1)
            while (len(c.shape) > len(t.shape)
                   and int(c.shape[-1]) == 1):
                c = squeeze(c, axis=-1)
            merged.append(_where(c.astype('bool'), t, f))
        return merged


class Switch:
    """``with switch.case(cond): ...`` / ``with switch.default(): ...`` —
    at replay, the FIRST case whose scalar condition holds runs; record-
    time executes each block once to capture it (outputs are overwritten
    at run time). Reference: fluid/layers/control_flow.py:Switch."""

    def __init__(self, name=None):
        self._prog = default_main_program()
        self._cases = []  # (cond or None, span)
        self._entered = False

    @contextlib.contextmanager
    def __wrap(self, cond):
        with _captured_span(self._prog) as holder:
            yield
        self._cases.append((cond, holder["span"]))

    def case(self, condition):
        return self.__wrap(condition)

    def default(self):
        return self.__wrap(None)

    def __enter__(self):
        self._entered = True
        return self

    def __exit__(self, *exc):
        cases = list(self._cases)

        def _dispatch():
            for cond, span in cases:
                if cond is None or _scalar_bool(cond):
                    Program._replay_entries(span)
                    return

        # structured entry: jitted replay lowers to a lax.cond chain
        self._prog._ops.append(("switch", _dispatch, cases))
        return False
