"""Federated-learning coordinator (reference distributed/ps/coordinator.py).

FedAvg over the shared-filesystem exchange: selector cohorts, weighted
averaging, client strategies, convergence on a distributed quadratic.
"""
import numpy as np
import pytest

from paddle_tpu.distributed.ps.coordinator import (ClientSelector,
                                                   Coordinator, FLClient,
                                                   FLStrategy)


def test_selector_fraction_and_determinism():
    sel = ClientSelector(fraction=0.5, seed=7)
    ids = [f"c{i}" for i in range(8)]
    a = sel.select(ids, round_idx=3)
    b = sel.select(ids, round_idx=3)
    assert a == b and len(a) == 4
    # cohorts vary across rounds (round_idx feeds the rng seed): over a
    # handful of rounds at 50% fraction some round must differ
    assert any(sel.select(ids, round_idx=r) != a for r in range(4, 12))
    assert ClientSelector(fraction=1.0).select(ids, 0) == sorted(ids)
    assert ClientSelector(fraction=1.0).select([], 0) == []


def test_fedavg_weighted_by_examples(tmp_path):
    coord = Coordinator(tmp_path, ClientSelector(1.0), timeout=10)

    def make_train(value, n):
        def train(r, state):
            return {"w": np.full_like(state["w"], value)}, n
        return train

    c1 = FLClient(tmp_path, "a", make_train(1.0, 1), timeout=10)
    c2 = FLClient(tmp_path, "b", make_train(4.0, 3), timeout=10)
    g0 = {"w": np.zeros(4, np.float32)}
    coord.publish_global(0, g0, coord.selector.select(coord.clients(), 0))
    assert c1.run_round(0) == FLStrategy.JOIN
    assert c2.run_round(0) == FLStrategy.JOIN
    # run_round republishes; pushes already in place so it returns avg
    new = coord.run_round(0, g0)
    np.testing.assert_allclose(new["w"], (1 * 1 + 4 * 3) / 4)


def test_unselected_client_waits(tmp_path):
    coord = Coordinator(tmp_path, ClientSelector(0.5, seed=0), timeout=10)
    clients = {i: FLClient(tmp_path, f"c{i}",
                           lambda r, s: ({"w": s["w"] + 1}, 1), timeout=10)
               for i in range(2)}
    g = {"w": np.zeros(2, np.float32)}
    cohort = coord.selector.select(coord.clients(), 0)
    assert len(cohort) == 1
    coord.publish_global(0, g, cohort)
    outcomes = {cid: c.run_round(0) for cid, c in clients.items()}
    joined = [k for k, v in outcomes.items() if v == FLStrategy.JOIN]
    waited = [k for k, v in outcomes.items() if v == FLStrategy.WAIT]
    assert len(joined) == 1 and len(waited) == 1


def test_federated_quadratic_converges(tmp_path):
    """4 clients with different local targets: FedAvg converges to the
    mean target — the canonical FedAvg sanity check."""
    rng = np.random.default_rng(0)
    targets = [rng.standard_normal(8).astype(np.float32) for _ in range(4)]
    mean_target = np.mean(targets, axis=0)

    def make_train(t):
        def train(r, state):
            w = state["w"].astype(np.float32)
            for _ in range(5):
                w = w - 0.2 * 2 * (w - t)
            return {"w": w}, 10
        return train

    coord = Coordinator(tmp_path, ClientSelector(1.0), timeout=10)
    clients = [FLClient(tmp_path, f"c{i}", make_train(t), timeout=10)
               for i, t in enumerate(targets)]
    g = {"w": np.zeros(8, np.float32)}
    for r in range(6):
        coord.publish_global(r, g, coord.selector.select(coord.clients(), r))
        for c in clients:
            c.run_round(r)
        g = coord.run_round(r, g)
    err = np.abs(g["w"] - mean_target).max()
    assert err < 1e-3, err


def test_finish_strategy(tmp_path):
    coord = Coordinator(tmp_path, timeout=5)
    c = FLClient(tmp_path, "x", lambda r, s: (s, 1), timeout=5)
    coord.publish_global(0, {"w": np.zeros(1)}, ["x"], final=True)
    assert c.run_round(0) == FLStrategy.FINISH


def test_timeout_names_missing_clients(tmp_path):
    coord = Coordinator(tmp_path, ClientSelector(1.0), timeout=0.5)
    FLClient(tmp_path, "ghost", lambda r, s: (s, 1))
    with pytest.raises(TimeoutError, match="ghost"):
        coord.run_round(0, {"w": np.zeros(1)})
