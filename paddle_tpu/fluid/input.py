"""fluid.input compat (embedding/one_hot free functions)."""
from .layers import embedding, one_hot  # noqa: F401
