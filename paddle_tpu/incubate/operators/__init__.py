"""incubate.operators — reference package spelling for the fused/graph
ops (reference python/paddle/incubate/operators/: graph_send_recv.py,
graph_sample_neighbors.py, graph_reindex.py, graph_khop_sampler.py,
softmax_mask_fuse*.py). Implementations live in incubate/graph_ops.py."""
import sys as _sys

from .. import graph_ops as _impl
from ..graph_ops import (graph_khop_sampler, graph_reindex,  # noqa: F401
                         graph_sample_neighbors, graph_send_recv,
                         identity_loss, softmax_mask_fuse,
                         softmax_mask_fuse_upper_triangle)

# reference-path submodule import compat (each reference file becomes an
# alias of the one implementation module):
for _name in ("graph_send_recv", "graph_sample_neighbors", "graph_reindex",
              "graph_khop_sampler", "softmax_mask_fuse",
              "softmax_mask_fuse_upper_triangle"):
    _sys.modules[f"{__name__}.{_name}"] = _impl
