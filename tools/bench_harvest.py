"""Harvest missing bench measurements across TPU-tunnel availability windows.

The axon TPU tunnel wedges intermittently (minutes-long dead windows between
usable ones). This loop probes the tunnel with a cheap subprocess matmul;
whenever it answers, it immediately runs bench.py restricted (via
PADDLE_TPU_BENCH_ONLY) to the configs that still lack a real number in
BENCH_SESSION.json. bench.py persists after every config, so even a window
that closes mid-run keeps what it caught. Exits when nothing is missing.

Usage: python tools/bench_harvest.py [--max-hours H]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SESSION = os.path.join(ROOT, "BENCH_SESSION.json")

CONFIGS = ["kernels", "bert_base_dp", "vit_b16", "ernie_moe_ep",
           "llama_seq8192", "int8_matmul", "llama_decode",
           "llama_fused_ce_ab", "llama_b8_selective_remat", "ctr_widedeep",
           "flash_blocks", "resnet50"]


def _session():
    try:
        with open(SESSION) as fh:
            return json.load(fh)
    except Exception:
        return {}


def missing(headline_cutoff=None):
    s = _session()
    sec = s.get("secondary") or {}
    out = []
    kern = s.get("kernels") or {}
    if not kern or "error" in kern or "skipped" in kern or any(
            isinstance(v, str) and v.startswith("FAIL") for v in kern.values()):
        out.append("kernels")
    for name in CONFIGS:
        if name == "kernels":
            continue
        v = sec.get(name)
        if not isinstance(v, dict) or "error" in v or "skipped" in v:
            out.append(name)
        elif name == "flash_blocks" and "best" not in v:
            out.append(name)  # every block config FAILed — not a result
    # a headline carried over from a previous session is a REPLAY, not
    # this round's measurement — recapture when it predates the cutoff.
    # measured_utc gets re-stamped by replay-only runs, so prefer the
    # headline's own stamp and treat an explicit replay marker as stale.
    when = s.get("headline_measured_utc") or s.get("measured_utc") or ""
    stale = headline_cutoff is not None and (
        when < headline_cutoff or s.get("replayed_from_session"))
    if not s.get("tokens_per_sec") or stale:
        out.insert(0, "headline")
    return out


def tunnel_up(timeout_s=90):
    try:
        subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; x = jnp.ones((128, 128)); "
             "print(float((x @ x).sum()))"],
            timeout=timeout_s, capture_output=True, check=True, cwd=ROOT)
        return True
    except Exception:
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-hours", type=float, default=8.0)
    ap.add_argument("--probe-interval", type=float, default=120.0)
    ap.add_argument("--refresh-headline-before", default=None,
                    help="ISO timestamp; a session headline older than "
                         "this is re-measured (default: harvest start)")
    args = ap.parse_args()
    cutoff = (args.refresh_headline_before
              or time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    # the comparison is lexicographic — an off-format timestamp would
    # silently always/never match, so fail fast
    import datetime
    datetime.datetime.strptime(cutoff, "%Y-%m-%dT%H:%M:%SZ")
    deadline = time.time() + args.max_hours * 3600

    while time.time() < deadline:
        todo = missing(headline_cutoff=cutoff)
        if not todo:
            print("harvest complete: all configs have real measurements")
            return 0
        if not tunnel_up():
            print(f"[{time.strftime('%H:%M:%S')}] tunnel down; "
                  f"missing={todo}; sleeping {args.probe_interval:.0f}s",
                  flush=True)
            time.sleep(args.probe_interval)
            continue
        print(f"[{time.strftime('%H:%M:%S')}] tunnel UP; harvesting {todo}",
              flush=True)
        env = dict(os.environ)
        env["PADDLE_TPU_BENCH_ONLY"] = ",".join(todo)
        env["PADDLE_TPU_BENCH_TOTAL_S"] = "3600"
        env["PADDLE_TPU_BENCH_BUDGET_S"] = "3300"
        env["PADDLE_TPU_BENCH_INIT_RETRIES"] = "1"
        # 420s killed BERT/ViT/MoE during first compile; give each config
        # room — the persistent compile cache makes retries cheap anyway
        env.setdefault("PADDLE_TPU_BENCH_PER_CONFIG_S", "900")
        try:
            subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                           env=env, cwd=ROOT, timeout=3900)
        except subprocess.TimeoutExpired:
            print("bench run exceeded 3900s; re-probing", flush=True)
    print("harvest deadline reached; still missing: "
          f"{missing(headline_cutoff=cutoff)}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
