"""Core tensor op parity vs numpy (SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_roundtrip():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([4]).numpy().sum() == 4
    np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])
    np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
    np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                               np.linspace(0, 1, 5), rtol=1e-6)
    assert paddle.eye(3).numpy()[1, 1] == 1


def test_arithmetic_dunders():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((x + y).numpy(), [5, 7, 9])
    np.testing.assert_allclose((x * y).numpy(), [4, 10, 18])
    np.testing.assert_allclose((y - x).numpy(), [3, 3, 3])
    np.testing.assert_allclose((y / x).numpy(), [4, 2.5, 2], rtol=1e-6)
    np.testing.assert_allclose((x ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((-x).numpy(), [-1, -2, -3])
    np.testing.assert_allclose((1.0 + x).numpy(), [2, 3, 4])
    np.testing.assert_allclose((10.0 / x).numpy(), [10, 5, 10 / 3], rtol=1e-6)


def test_matmul():
    a = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    b = np.random.default_rng(1).normal(size=(4, 5)).astype(np.float32)
    out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)
    out_t = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b.T),
                          transpose_y=True)
    np.testing.assert_allclose(out_t.numpy(), a @ b, rtol=1e-5)


def test_reductions():
    a = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    x = paddle.to_tensor(a)
    np.testing.assert_allclose(paddle.sum(x).numpy(), a.sum(), rtol=1e-5)
    np.testing.assert_allclose(paddle.mean(x, axis=1).numpy(), a.mean(1),
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.max(x, axis=0, keepdim=True).numpy(),
                               a.max(0, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(x.std().numpy(), a.std(ddof=1), rtol=1e-4)


def test_manipulation():
    a = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    x = paddle.to_tensor(a)
    assert paddle.reshape(x, [4, 6]).shape == [4, 6]
    assert paddle.transpose(x, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(x, 1).shape == [2, 12]
    parts = paddle.split(x, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts2 = paddle.split(x, [1, 2], axis=1)
    assert parts2[1].shape == [2, 2, 4]
    s = paddle.stack([x, x], axis=0)
    assert s.shape == [2, 2, 3, 4]
    c = paddle.concat([x, x], axis=2)
    assert c.shape == [2, 3, 8]
    assert paddle.squeeze(paddle.unsqueeze(x, 0), 0).shape == [2, 3, 4]
    np.testing.assert_allclose(paddle.flip(x, [0]).numpy(), a[::-1])


def test_indexing():
    a = np.arange(12).reshape(3, 4).astype(np.float32)
    x = paddle.to_tensor(a)
    np.testing.assert_allclose(x[1].numpy(), a[1])
    np.testing.assert_allclose(x[:, 2].numpy(), a[:, 2])
    np.testing.assert_allclose(x[0:2, 1:3].numpy(), a[0:2, 1:3])
    x[0, 0] = 100.0
    assert x.numpy()[0, 0] == 100.0


def test_comparison_and_logic():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((x < y).numpy(), [True, False, False])
    np.testing.assert_array_equal((x == y).numpy(), [False, True, False])
    np.testing.assert_array_equal(
        paddle.logical_and(x > 1, y > 1).numpy(), [False, True, False])
    assert bool(paddle.allclose(x, x))


def test_search_sort():
    a = np.asarray([[3.0, 1.0, 2.0], [9.0, 7.0, 8.0]], dtype=np.float32)
    x = paddle.to_tensor(a)
    assert int(paddle.argmax(x, axis=1).numpy()[0]) == 0
    np.testing.assert_allclose(paddle.sort(x, axis=1).numpy(),
                               np.sort(a, axis=1))
    vals, idx = paddle.topk(x, 2, axis=1)
    np.testing.assert_allclose(vals.numpy(), [[3, 2], [9, 8]])
    w = paddle.where(x > 2, x, paddle.zeros_like(x))
    np.testing.assert_allclose(w.numpy(), [[3, 0, 0], [9, 7, 8]])


def test_linalg():
    a = np.random.default_rng(0).normal(size=(4, 4)).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    x = paddle.to_tensor(spd)
    np.testing.assert_allclose(
        paddle.linalg.cholesky(x).numpy(), np.linalg.cholesky(spd), rtol=1e-4,
        atol=1e-4)
    np.testing.assert_allclose(paddle.linalg.inv(x).numpy(),
                               np.linalg.inv(spd), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(paddle.linalg.norm(x).numpy(),
                               np.linalg.norm(spd), rtol=1e-5)


def test_einsum():
    a = np.random.default_rng(0).normal(size=(2, 3)).astype(np.float32)
    b = np.random.default_rng(1).normal(size=(3, 4)).astype(np.float32)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_cast_dtype():
    x = paddle.to_tensor([1.5, 2.5])
    y = x.astype("int32")
    assert y.numpy().dtype == np.int32
    z = paddle.cast(x, paddle.bfloat16)
    assert str(z.dtype) == "bfloat16"


def test_gather_scatter():
    a = np.arange(12).reshape(4, 3).astype(np.float32)
    x = paddle.to_tensor(a)
    idx = paddle.to_tensor(np.asarray([0, 2]))
    np.testing.assert_allclose(paddle.gather(x, idx, axis=0).numpy(), a[[0, 2]])
    upd = paddle.scatter(x, idx, paddle.zeros([2, 3]))
    assert upd.numpy()[0].sum() == 0 and upd.numpy()[2].sum() == 0


def test_random_reproducible():
    paddle.seed(42)
    a = paddle.rand([3, 3]).numpy()
    paddle.seed(42)
    b = paddle.rand([3, 3]).numpy()
    np.testing.assert_allclose(a, b)
