"""fluid.profiler compat (reference python/paddle/fluid/profiler.py):
start/stop/profiler context over the jax-profiler-backed paddle profiler."""
import contextlib

from ..profiler import Profiler as _Profiler

_active = None


def start_profiler(state="All", tracer_option="Default"):
    global _active
    if _active is None:
        _active = _Profiler()
        _active.start()


def stop_profiler(sorted_key=None, profile_path=None):
    global _active
    if _active is not None:
        _active.stop()
        _active = None


def reset_profiler():
    pass


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             tracer_option="Default"):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
