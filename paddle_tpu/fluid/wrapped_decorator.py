"""Reference: python/paddle/fluid/wrapped_decorator.py — decorator
helpers imported by ecosystem libraries (`wrap_decorator`,
`signature_safe_contextmanager`)."""
from __future__ import annotations

import contextlib

__all__ = ["wrap_decorator", "signature_safe_contextmanager"]


def wrap_decorator(decorator_func):
    """Return a decorator that preserves the wrapped function's
    signature (the reference uses the `decorator` package; functools
    keeps __wrapped__ which is enough for inspect.signature)."""
    import functools

    @functools.wraps(decorator_func)
    def __impl__(func):
        decorated = decorator_func(func)
        functools.update_wrapper(decorated, func)
        return decorated

    return __impl__


def signature_safe_contextmanager(func):
    return contextlib.contextmanager(func)
