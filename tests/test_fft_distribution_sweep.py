"""FFT namespace parity vs numpy.fft and distribution sampling statistics.

Reference: python/paddle/fft.py (wraps fft kernels), paddle/distribution.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

RNG = np.random.default_rng(5)


def _t(a):
    return paddle.to_tensor(a)


FFT_CASES = [
    ("fft", np.fft.fft), ("ifft", np.fft.ifft),
    ("rfft", np.fft.rfft), ("fft2", np.fft.fft2),
    ("ifft2", np.fft.ifft2),
]


@pytest.mark.parametrize("name,ref", FFT_CASES, ids=[c[0] for c in FFT_CASES])
def test_fft_parity(name, ref):
    x = RNG.standard_normal((4, 8)).astype(np.float32)
    got = getattr(paddle.fft, name)(_t(x)).numpy()
    want = ref(x)
    np.testing.assert_allclose(got, want.astype(got.dtype), rtol=1e-4,
                               atol=1e-5)


def test_fftfreq_shift():
    np.testing.assert_allclose(paddle.fft.fftfreq(8, d=0.5).numpy(),
                               np.fft.fftfreq(8, 0.5), rtol=1e-6)
    x = RNG.standard_normal((8,)).astype(np.float32)
    np.testing.assert_allclose(paddle.fft.fftshift(_t(x)).numpy(),
                               np.fft.fftshift(x), rtol=1e-6)


def test_irfft_roundtrip():
    x = RNG.standard_normal((16,)).astype(np.float32)
    back = paddle.fft.irfft(paddle.fft.rfft(_t(x)), n=16).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)


def test_stft_istft_roundtrip():
    x = RNG.standard_normal((1, 512)).astype(np.float32)
    spec = paddle.signal.stft(_t(x), n_fft=128, hop_length=32)
    back = paddle.signal.istft(spec, n_fft=128, hop_length=32)
    n = min(back.shape[-1], 512)
    np.testing.assert_allclose(back.numpy()[0, 64:n - 64],
                               x[0, 64:n - 64], rtol=1e-3, atol=1e-3)


def test_normal_sampling_stats():
    paddle.seed(7)
    d = paddle.distribution.Normal(loc=2.0, scale=0.5)
    s = d.sample([20000]).numpy()
    assert abs(s.mean() - 2.0) < 0.02
    assert abs(s.std() - 0.5) < 0.02
    np.testing.assert_allclose(
        float(d.log_prob(paddle.to_tensor(2.0)).numpy()),
        -np.log(0.5 * np.sqrt(2 * np.pi)), rtol=1e-5)


def test_categorical_sampling_stats():
    paddle.seed(8)
    probs = np.asarray([0.1, 0.2, 0.7], np.float32)
    d = paddle.distribution.Categorical(paddle.to_tensor(np.log(probs)))
    s = d.sample([30000]).numpy()
    freq = np.bincount(s.ravel().astype(int), minlength=3) / s.size
    np.testing.assert_allclose(freq, probs, atol=0.02)


def test_kl_divergence_normal():
    p = paddle.distribution.Normal(0.0, 1.0)
    q = paddle.distribution.Normal(1.0, 2.0)
    kl = float(paddle.distribution.kl_divergence(p, q).numpy())
    want = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(kl, want, rtol=1e-5)


def test_multivariate_normal_diag():
    import numpy as np

    from paddle_tpu.distribution import MultivariateNormalDiag

    d = MultivariateNormalDiag(np.zeros(3, np.float32),
                               np.ones(3, np.float32))
    s = d.sample((500,))
    assert list(s.shape) == [500, 3]
    lp = np.asarray(d.log_prob(s)._data)
    assert np.isfinite(lp).all()
    d2 = MultivariateNormalDiag(np.ones(3, np.float32),
                                2 * np.ones(3, np.float32))
    kl = float(np.asarray(d.kl_divergence(d2)._data))
    want = 0.5 * 3 * (0.25 + 0.25 - 1 + np.log(4.0))
    np.testing.assert_allclose(kl, want, rtol=1e-5)
    ent = float(np.asarray(d.entropy()._data))
    np.testing.assert_allclose(ent, 1.5 * (1 + np.log(2 * np.pi)),
                               rtol=1e-5)
    # log_prob of the mean is the density peak
    peak = float(np.asarray(d.log_prob(
        np.zeros((1, 3), np.float32))._data)[0])
    np.testing.assert_allclose(peak, -1.5 * np.log(2 * np.pi), rtol=1e-5)
