"""Collective communication API.

Reference: python/paddle/distributed/collective.py (ProcessGroupNCCL-backed
all_reduce / all_gather / ... with ring ids). TPU-native mapping:

* Compiled path (the perf path): collectives are *implied* by shardings
  under pjit — user code rarely calls these.
* Manual-SPMD path: inside ``shard_map`` (ring attention, pipeline,
  custom kernels) these functions lower to jax.lax collectives
  (psum/all_gather/ppermute/all_to_all) over the mesh axis named by the
  Group.
* Eager, single controller: world_size == process count (1 locally), so the
  collectives are identity — matching paddle semantics where each rank holds
  its local tensor.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..tensor import Tensor, apply
from . import mesh as mesh_mod


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communicator == a (set of) mesh axis name(s)."""

    def __init__(self, rank=0, nranks=1, id=0, ranks=None, axis_names=("dp",)):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis_names = tuple(axis_names)

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return f"Group(axes={self.axis_names}, nranks={self.nranks})"


_default_group: Optional[Group] = None
_initialized = False


def init_parallel_env():
    """Initialize distributed state. Multi-host: jax.distributed via the
    standard env (PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID or JAX coords)."""
    global _initialized, _default_group
    if _initialized:
        return _default_group
    n_proc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get(
        "MASTER_ADDR_PORT")
    if n_proc > 1 and coord:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n_proc, process_id=pid)
    _default_group = Group(rank=pid, nranks=max(n_proc, 1) if n_proc > 1
                           else 1, axis_names=("dp", "sharding"))
    _initialized = True
    return _default_group


def is_initialized():
    return _initialized


def get_rank(group=None):
    return (group or _default_group or Group()).rank if _initialized or group \
        else int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    if _default_group is not None:
        return _default_group.nranks
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def new_group(ranks=None, backend=None, axis_names=None):
    g = Group(rank=0, nranks=len(ranks) if ranks else get_world_size(),
              ranks=ranks, axis_names=tuple(axis_names or ("dp",)))
    return g


def get_group(id=0):
    return _default_group


def _in_shard_map(axis_names) -> bool:
    """True when called under a shard_map/pmap trace that binds these axes."""
    try:
        jax.lax.axis_index(axis_names[0] if len(axis_names) == 1
                           else tuple(axis_names))
        return True
    except NameError:
        return False
    except Exception:
        return False


def _axes(group):
    g = group or _default_group
    return g.axis_names if g is not None else ("dp",)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=True):
    axes = _axes(group)
    if _in_shard_map(axes):
        ax = axes if len(axes) > 1 else axes[0]

        def _pprod(a, axis_name):
            # no pprod primitive: product = all_gather then reduce
            return jnp.prod(jax.lax.all_gather(a, axis_name), axis=0)

        fns = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin, ReduceOp.AVG: jax.lax.pmean,
               ReduceOp.PROD: _pprod}
        out = apply(lambda a: fns[op](a, ax), tensor)
        tensor._data = out._data
        tensor._node = out._node
        tensor._out_index = out._out_index
        return tensor
    return tensor  # single-controller eager: already the global value


def all_gather(tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=True):
    axes = _axes(group)
    if _in_shard_map(axes):
        ax = axes if len(axes) > 1 else axes[0]
        gathered = apply(lambda a: jax.lax.all_gather(a, ax), tensor)
        if isinstance(tensor_list, list):
            n = gathered.shape[0]
            for i in range(n):
                tensor_list.append(gathered[i])
        return gathered
    if isinstance(tensor_list, list):
        tensor_list.append(tensor)
    return tensor


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=True):
    return all_reduce(tensor, op, group)


def broadcast(tensor, src=0, group=None, sync_op=True, use_calc_stream=True):
    axes = _axes(group)
    if _in_shard_map(axes):
        ax = axes[0]
        # select src's value on every member
        out = apply(lambda a: jax.lax.all_gather(a, ax)[src], tensor)
        tensor._data = out._data
        tensor._node = out._node
        tensor._out_index = out._out_index
        return tensor
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True,
            use_calc_stream=True):
    axes = _axes(group)
    if _in_shard_map(axes):
        ax = axes[0]
        idx = jax.lax.axis_index(ax)
        stacked = jnp.stack([t._data for t in tensor_list]) if tensor_list \
            else tensor._data
        tensor._data = jax.lax.dynamic_index_in_dim(stacked, idx, keepdims=False)
        return tensor
    if tensor_list:
        tensor._data = tensor_list[src]._data
    return tensor


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True,
             use_calc_stream=True):
    axes = _axes(group)
    if _in_shard_map(axes):
        ax = axes[0]
        stacked = apply(lambda *xs: jnp.stack(xs, axis=0), *in_tensor_list)
        out = apply(lambda s: jax.lax.all_to_all(s, ax, split_axis=0,
                                                 concat_axis=0, tiled=False),
                    stacked)
        for i in range(len(in_tensor_list)):
            out_tensor_list.append(out[i])
        return out
    out_tensor_list.extend(in_tensor_list)
    return in_tensor_list


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    axes = _axes(group)
    if _in_shard_map(axes):
        ax = axes[0]
        out = apply(lambda a: jax.lax.all_to_all(
            a, ax, split_axis=0, concat_axis=0, tiled=True), in_tensor)
        if out_tensor is not None:
            out_tensor._data = out._data
        return out
    if out_tensor is not None:
        out_tensor._data = in_tensor._data
    return in_tensor


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True, use_calc_stream=True):
    """Reduce then scatter along dim 0 (reference:
    collective.py::reduce_scatter / ProcessGroupNCCL::ReduceScatter).
    Inside shard_map this is XLA's fused reduce-scatter (psum_scatter),
    the collective that makes ZeRO gradients ride ICI at half the
    all-reduce cost."""
    axes = _axes(group)
    src = tensor if tensor_list is None else apply(
        lambda *xs: jnp.concatenate(xs, axis=0), *tensor_list)
    if _in_shard_map(axes):
        ax = axes if len(axes) > 1 else axes[0]
        if op != ReduceOp.SUM:
            raise ValueError("reduce_scatter supports SUM on TPU")
        out = apply(lambda a: jax.lax.psum_scatter(a, ax, tiled=True), src)
        tensor._data = out._data
        tensor._node = out._node
        tensor._out_index = out._out_index
        return tensor
    return src  # single-controller eager: already the global value


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=True):
    # point-to-point maps to ppermute inside shard_map (see ops.pipeline);
    # eager single-controller: no-op
    return tensor


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=True):
    return tensor


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


def barrier(group=None):
    # jax dispatch is ordered per device; block host on a tiny computation
    jnp.zeros(()).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and hasattr(tensor._data, "block_until_ready"):
        tensor._data.block_until_ready()


def destroy_process_group(group=None):
    global _initialized, _default_group
    _initialized = False
    _default_group = None


def split(*args, **kwargs):
    raise NotImplementedError(
        "paddle.distributed.split: use fleet.meta_parallel Column/Row "
        "parallel layers")


# -- in-shard_map helpers used by ring attention / pipeline ---------------
def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


def broadcast_object_list(object_list, src=0, group=None):
    """Single-controller: every rank already holds the same python objects
    (reference collective.py broadcast_object_list pickles over NCCL)."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Single-controller: rank 0's view IS the global view; hand back the
    first slot (reference scatters pickled slices per rank)."""
    if in_object_list:
        out_object_list.append(in_object_list[0])
    return out_object_list


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    barrier(group)


class P2POp:
    """Batched p2p descriptor (reference collective.py:2378). Under SPMD
    the batch is expressed as one ppermute; this object records intent for
    batch_isend_irecv."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Reference collective.py:2436. Inside shard_map the isend/irecv pairs
    coalesce into ppermute; eager single-controller they are no-ops that
    complete immediately. Returns completed 'request' placeholders."""
    reqs = []
    for p in p2p_op_list:
        p.op(p.tensor, p.peer, p.group)
        reqs.append(p)
    return reqs


def all_to_all_single(out_tensor, in_tensor, in_split_sizes=None,
                      out_split_sizes=None, group=None, sync_op=True):
    """Reference collective.py all_to_all_single: equal-split all-to-all on
    one tensor. Inside shard_map → lax.all_to_all over the group axis;
    eager single-controller → identity copy."""
    axes = _axes(group)
    if _in_shard_map(axes):
        ax = axes[0]
        n = jax.lax.axis_size(ax)
        out = apply(lambda a: jax.lax.all_to_all(
            a.reshape(n, -1, *a.shape[1:]), ax, split_axis=0,
            concat_axis=0, tiled=False).reshape(a.shape), in_tensor)
        out_tensor._data = out._data
        out_tensor._node = out._node
        out_tensor._out_index = out._out_index
        return out_tensor
    out_tensor._data = in_tensor._data
    return out_tensor
