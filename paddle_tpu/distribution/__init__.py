"""Probability distributions. Reference: python/paddle/distribution/*."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.random_seed import next_key
from ..tensor import Tensor, apply
from ..tensor_ops._factory import raw


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        # reference distribution.py:54 — subclasses pass their shapes up
        self._batch_shape = tuple(batch_shape) \
            if not isinstance(batch_shape, tuple) else batch_shape
        self._event_shape = tuple(event_shape) \
            if not isinstance(event_shape, tuple) else event_shape

    @property
    def batch_shape(self):
        return getattr(self, "_batch_shape", ())

    @property
    def event_shape(self):
        return getattr(self, "_event_shape", ())

    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        from ..tensor_ops.math import exp
        return exp(self.log_prob(value))

    # reference distribution/distribution.py defines prob on the base;
    # subclasses with a direct density can override
    prob = probs


def _coerce(v):
    """Scalars, lists/tuples and ndarrays -> float32 Tensor (the
    reference's broadcastable-parameter contract)."""
    if isinstance(v, Tensor):
        return v
    return Tensor(jnp.asarray(v, dtype=jnp.float32))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _coerce(loc)
        self.scale = _coerce(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return apply(lambda s: s * s, self.scale)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(
            raw(self.loc).shape, raw(self.scale).shape))
        eps = jax.random.normal(next_key(), shp)
        return Tensor(raw(self.loc) + raw(self.scale) * eps)

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        return apply(lambda v, m, s: -((v - m) ** 2) / (2 * s * s)
                     - jnp.log(s) - 0.5 * math.log(2 * math.pi),
                     value, self.loc, self.scale)

    def entropy(self):
        return apply(lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                     self.scale)

    def kl_divergence(self, other):
        # 0.5*log(ratio^2) rather than log(ratio): identical for positive
        # scales and matches the reference's var-ratio formulation
        # (kl uses squared scales) on degenerate sign cases
        return apply(lambda m1, s1, m2, s2:
                     0.5 * jnp.log((s2 / s1) ** 2)
                     + (s1 ** 2 + (m1 - m2) ** 2) / (2 * s2 ** 2) - 0.5,
                     self.loc, self.scale, other.loc, other.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _coerce(low)
        self.high = _coerce(high)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(
            raw(self.low).shape, raw(self.high).shape))
        u = jax.random.uniform(next_key(), shp)
        return Tensor(raw(self.low) + (raw(self.high) - raw(self.low)) * u)

    def log_prob(self, value):
        return apply(lambda v, lo, hi: jnp.where(
            (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf),
            value, self.low, self.high)

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) else Tensor(jnp.asarray(logits))

    def sample(self, shape=()):
        return Tensor(jax.random.categorical(
            next_key(), raw(self.logits), shape=tuple(shape) + raw(self.logits).shape[:-1] if shape else None))

    def _gather(self, scores, value):
        """Reference categorical.py:303 gather semantics: 1-D scores
        gather flat then reshape to value.shape; batched scores with a
        1-D value broadcast the value across all distributions (output
        [..., len(value)]); otherwise take_along_axis keeps dims."""
        idx = raw(value).astype(jnp.int32)

        def f(sc):
            if sc.ndim == 1:
                return jnp.take(sc, idx.reshape(-1)).reshape(idx.shape)
            if idx.ndim == 1:
                bshape = (1,) * (sc.ndim - 1) + (-1,)
                return jnp.take_along_axis(sc, idx.reshape(bshape), -1)
            return jnp.take_along_axis(sc, idx, -1)

        return apply(f, scores)

    def probs(self, value):
        # reference categorical.py:119 quirk mirrored exactly: probs and
        # log_prob LINEARLY normalize the given scores (self._prob =
        # logits / logits.sum), while entropy/kl use softmax
        return self._gather(apply(
            lambda lg: lg / jnp.sum(lg, -1, keepdims=True), self.logits),
            value)

    def log_prob(self, value):
        return apply(lambda p: jnp.log(p), self.probs(value))

    def entropy(self):
        def f(lg):
            p = jax.nn.softmax(lg, -1)
            return -jnp.sum(p * jax.nn.log_softmax(lg, -1), axis=-1)
        return apply(f, self.logits)

    def kl_divergence(self, other):
        """KL(self || other) over the category axis (reference
        distribution/categorical.py kl_divergence — keepdims, so the
        result is [..., 1] like the C++ op)."""
        def f(lg, lg2):
            p = jax.nn.softmax(lg, -1)
            return jnp.sum(p * (jax.nn.log_softmax(lg, -1)
                                - jax.nn.log_softmax(lg2, -1)),
                           axis=-1, keepdims=True)
        return apply(f, self.logits, other.logits)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = probs if isinstance(probs, Tensor) else Tensor(jnp.asarray(float(probs)))

    def sample(self, shape=()):
        p = raw(self.probs_)
        return Tensor(jax.random.bernoulli(
            next_key(), p, tuple(shape) + p.shape).astype(jnp.float32))

    def log_prob(self, value):
        return apply(lambda v, p: v * jnp.log(jnp.clip(p, 1e-12, None)) +
                     (1 - v) * jnp.log(jnp.clip(1 - p, 1e-12, None)),
                     value, self.probs_)

    def entropy(self):
        return apply(lambda p: -(p * jnp.log(jnp.clip(p, 1e-12, None)) +
                                 (1 - p) * jnp.log(jnp.clip(1 - p, 1e-12, None))),
                     self.probs_)


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions; entropy via the
    Bregman-divergence identity when _log_normalizer is differentiable.
    Reference: distribution/exponential_family.py."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        # reference exponential_family.py: only distributions with a
        # known carrier measure override this; the Bregman entropy MUST
        # refuse otherwise (TestExponentialFamilyException contract)
        raise NotImplementedError

    def entropy(self):
        # reference exponential_family.py entropy: ELEMENTWISE Bregman
        # identity H = logZ(η) - Σ η·∇logZ(η) - carrier, per batch
        # element. _log_normalizer implementations use paddle ops, so
        # thread Tensors in and raw values out around jax.grad.
        nat = [raw(p) for p in self._natural_parameters]

        def f(*ps):
            out = self._log_normalizer(*[Tensor(p) for p in ps])
            out = raw(out)
            return jnp.sum(out), out

        (_, log_norm), grads = jax.value_and_grad(
            f, argnums=tuple(range(len(nat))), has_aux=True)(*nat)
        ent = log_norm - self._mean_carrier_measure
        for p, g in zip(nat, grads):
            ent = ent - p * g
        return Tensor(ent)


class Beta(ExponentialFamily):
    def __init__(self, alpha, concentration1=None, name=None, beta=None):
        b = beta if beta is not None else concentration1
        self.alpha = _coerce(alpha)
        self.beta = _coerce(b)


    @property
    def _natural_parameters(self):
        return (self.alpha, self.beta)

    def _log_normalizer(self, x, y):
        from ..tensor_ops import lgamma
        return lgamma(x) + lgamma(y) - lgamma(x + y)

    @property
    def mean(self):
        return apply(lambda a, b: a / (a + b), self.alpha, self.beta)

    @property
    def variance(self):
        return apply(lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                     self.alpha, self.beta)

    def entropy(self):
        from jax.scipy.special import betaln, digamma

        def f(a, b):
            return (betaln(a, b) - (a - 1) * digamma(a)
                    - (b - 1) * digamma(b)
                    + (a + b - 2) * digamma(a + b))

        return apply(f, self.alpha, self.beta)

    def sample(self, shape=()):
        return Tensor(jax.random.beta(next_key(), raw(self.alpha),
                                      raw(self.beta), tuple(shape) or None))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        return apply(lambda v, a, b: (a - 1) * jnp.log(v) +
                     (b - 1) * jnp.log1p(-v) - betaln(a, b),
                     value, self.alpha, self.beta)


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        self.concentration = concentration if isinstance(concentration, Tensor) \
            else Tensor(jnp.asarray(concentration, dtype=jnp.float32))


    @property
    def _natural_parameters(self):
        return (self.concentration,)

    @property
    def event_shape(self):
        return tuple(raw(self.concentration).shape[-1:])

    def _log_normalizer(self, x):
        from jax.scipy.special import gammaln
        return apply(lambda c: jnp.sum(gammaln(c), -1)
                     - gammaln(jnp.sum(c, -1)), x)

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(next_key(), raw(self.concentration),
                                           tuple(shape) or ()))

    @property
    def mean(self):
        return apply(lambda c: c / jnp.sum(c, -1, keepdims=True),
                     self.concentration)

    @property
    def variance(self):
        def f(c):
            a0 = jnp.sum(c, -1, keepdims=True)
            return c * (a0 - c) / (a0 * a0 * (a0 + 1))
        return apply(f, self.concentration)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        def f(v, c):
            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + gammaln(jnp.sum(c, -1)) - jnp.sum(gammaln(c), -1))

        return apply(f, value, self.concentration)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln

        def f(c):
            a0 = jnp.sum(c, -1)
            k = c.shape[-1]
            lnB = jnp.sum(gammaln(c), -1) - gammaln(a0)
            return (lnB + (a0 - k) * digamma(a0)
                    - jnp.sum((c - 1) * digamma(c), -1))

        return apply(f, self.concentration)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(jnp.asarray(float(loc)))
        self.scale = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(float(scale)))

    def sample(self, shape=()):
        shp = tuple(shape) + raw(self.loc).shape
        return Tensor(raw(self.loc) + raw(self.scale) *
                      jax.random.gumbel(next_key(), shp))


def _kl_beta_beta(p, q):
    """KL(Beta(a1,b1) || Beta(a2,b2)) closed form (reference kl.py)."""
    from jax.scipy.special import betaln, digamma

    def f(a1, b1, a2, b2):
        return (betaln(a2, b2) - betaln(a1, b1)
                + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
                + (a2 - a1 + b2 - b1) * digamma(a1 + b1))

    return apply(f, p.alpha, p.beta, q.alpha, q.beta)


def _kl_dirichlet_dirichlet(p, q):
    """KL between Dirichlets (reference kl.py _kl_dirichlet_dirichlet)."""
    from jax.scipy.special import digamma, gammaln

    def f(c1, c2):
        lnB1 = jnp.sum(gammaln(c1), -1) - gammaln(jnp.sum(c1, -1))
        lnB2 = jnp.sum(gammaln(c2), -1) - gammaln(jnp.sum(c2, -1))
        dg = digamma(c1) - digamma(jnp.sum(c1, -1, keepdims=True))
        return lnB2 - lnB1 + jnp.sum((c1 - c2) * dg, -1)

    return apply(f, p.concentration, q.concentration)


def kl_expfamily_expfamily(p, q):
    """Generic exponential-family KL via the Bregman divergence of the
    log normalizer (reference kl.py _kl_expfamily_expfamily):
    KL(p||q) = logZ(η_q) - logZ(η_p) - (η_q - η_p)·∇logZ(η_p)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            "expfamily KL needs matching distribution types")
    np_ = [raw(t) for t in p._natural_parameters]
    nq = [raw(t) for t in q._natural_parameters]

    def logz(*ps):
        out = raw(p._log_normalizer(*[Tensor(v) for v in ps]))
        return jnp.sum(out), out

    # ELEMENTWISE Bregman divergence, like the reference — the result
    # has the distributions' batch shape, not a scalar
    (_, lp_el), grads = jax.value_and_grad(
        logz, argnums=tuple(range(len(np_))), has_aux=True)(*np_)
    lq_el = raw(q._log_normalizer(*[Tensor(v) for v in nq]))
    out = lq_el - lp_el
    n_event = len(getattr(p, "event_shape", ()) or ())
    for etap, etaq, g in zip(np_, nq, grads):
        term = (etaq - etap) * g
        if n_event > 0:  # reference kl.py: sum over the event dims
            term = jnp.sum(term, axis=tuple(range(term.ndim - n_event,
                                                  term.ndim)))
        out = out - term
    return Tensor(out)


_kl_expfamily_expfamily = kl_expfamily_expfamily  # reference kl.py name


def kl_divergence(p, q):
    fn = _registered_kl(p, q)
    if fn is not None:
        return fn(p, q)
    if isinstance(p, Normal) and isinstance(q, Normal):
        return p.kl_divergence(q)
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        # delegate to the method (reference kl.py does the same) so the
        # module-level API keeps the [..., 1] keepdims shape contract
        return p.kl_divergence(q)
    if isinstance(p, Beta) and isinstance(q, Beta):
        return _kl_beta_beta(p, q)
    if isinstance(p, Dirichlet) and isinstance(q, Dirichlet):
        return _kl_dirichlet_dirichlet(p, q)
    if isinstance(p, ExponentialFamily) and isinstance(q,
                                                      ExponentialFamily) \
            and type(p) is type(q):
        return kl_expfamily_expfamily(p, q)
    raise NotImplementedError(f"kl({type(p).__name__}, {type(q).__name__})")


class Multinomial(Distribution):
    """total_count trials over categorical probs. Reference:
    distribution/multinomial.py."""

    def __init__(self, total_count, probs, name=None):
        if total_count < 1:
            raise ValueError("total_count should be >= 1")
        self.total_count = int(total_count)
        self.probs_ = probs if isinstance(probs, Tensor) \
            else Tensor(jnp.asarray(probs))

    @property
    def probs(self):
        return self.probs_

    @property
    def mean(self):
        return apply(lambda p: self.total_count * p, self.probs_)

    @property
    def variance(self):
        return apply(lambda p: self.total_count * p * (1 - p), self.probs_)

    def sample(self, shape=()):
        p = raw(self.probs_)
        logits = jnp.log(jnp.clip(p, 1e-30, None))
        draws = jax.random.categorical(
            next_key(), logits,
            shape=tuple(shape) + (self.total_count,) + p.shape[:-1])
        onehot = jax.nn.one_hot(draws, p.shape[-1], dtype=jnp.float32)
        # sum over the trials axis (first after the sample shape)
        counts = jnp.sum(onehot, axis=len(tuple(shape)))
        return Tensor(counts)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        def f(v, p):
            logp = jnp.log(jnp.clip(p, 1e-30, None))
            return (gammaln(self.total_count + 1.0)
                    - jnp.sum(gammaln(v + 1.0), axis=-1)
                    + jnp.sum(v * logp, axis=-1))
        return apply(f, value, self.probs_)

    def entropy(self):
        # exact entropy has no closed form; Monte-Carlo estimate matching
        # the reference's docs precision is overkill — use the categorical
        # decomposition bound used in practice
        c = Categorical(apply(lambda p: jnp.log(
            jnp.clip(p, 1e-30, None)), self.probs_))
        return apply(lambda e: self.total_count * e, c.entropy())


class Independent(Distribution):
    """Reinterpret trailing batch dims as event dims. Reference:
    distribution/independent.py."""

    def __init__(self, base, reinterpreted_batch_rank=1):
        if not isinstance(base, Distribution):
            raise TypeError(
                f"Expected base to be a Distribution, got {type(base)}")
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return apply(lambda a: jnp.sum(
            a, axis=tuple(range(a.ndim - self.rank, a.ndim))), lp)

    def entropy(self):
        e = self.base.entropy()
        return apply(lambda a: jnp.sum(
            a, axis=tuple(range(a.ndim - self.rank, a.ndim))), e)


class TransformedDistribution(Distribution):
    """Push a base distribution through invertible transforms. Each
    transform needs forward(x), inverse(y),
    forward_log_det_jacobian(x). Reference:
    distribution/transformed_distribution.py."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = getattr(self.base, "rsample", self.base.sample)(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = value
        log_det = None
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ld = t.forward_log_det_jacobian(x)
            log_det = ld if log_det is None else log_det + ld
            y = x
        lp = self.base.log_prob(y)
        return lp - log_det if log_det is not None else lp


# -- transforms (full reference surface in distribution/transform.py) ------

from .transform import (AbsTransform, AffineTransform,  # noqa: E402,F401
                        ChainTransform, ExpTransform,
                        IndependentTransform, PowerTransform,
                        ReshapeTransform, SigmoidTransform,
                        SoftmaxTransform, StackTransform,
                        StickBreakingTransform, TanhTransform, Transform,
                        Type)
from . import constraint, variable  # noqa: E402,F401


# -- kl registry -----------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a KL implementation for a type pair.
    Reference: distribution/kl.py::register_kl."""
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def _registered_kl(p, q):
    match = None
    score = -1
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            s = len(type(p).__mro__) + len(type(q).__mro__)
            if s > score:
                match, score = fn, s
    return match


class MultivariateNormalDiag(Distribution):
    """Multivariate normal with diagonal covariance (reference
    fluid/layers/distributions.py:MultivariateNormalDiag): loc [..., D],
    scale as the diagonal entries [..., D]."""

    def __init__(self, loc, scale):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(
            jnp.asarray(loc, jnp.float32))
        self.scale = scale if isinstance(scale, Tensor) else Tensor(
            jnp.asarray(scale, jnp.float32))
        super().__init__()

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return apply(lambda s: s * s, self.scale)

    def sample(self, shape=()):
        from ..framework.random_seed import next_key
        shape = tuple(shape)
        key = next_key()

        def _s(m, s):
            full = shape + m.shape
            return m + s * jax.random.normal(key, full, m.dtype)

        return apply(_s, self.loc, self.scale)

    def entropy(self):
        def _e(s):
            d = s.shape[-1]
            return (0.5 * d * (1.0 + jnp.log(jnp.asarray(2 * jnp.pi)))
                    + jnp.sum(jnp.log(s), axis=-1))
        return apply(_e, self.scale)

    def log_prob(self, value):
        def _lp(v, m, s):
            z = (v - m) / s
            return (-0.5 * jnp.sum(z * z, axis=-1)
                    - jnp.sum(jnp.log(s), axis=-1)
                    - 0.5 * m.shape[-1] * jnp.log(jnp.asarray(2 * jnp.pi)))
        return apply(_lp, value, self.loc, self.scale)

    def kl_divergence(self, other):
        def _kl(m1, s1, m2, s2):
            var1, var2 = s1 * s1, s2 * s2
            return 0.5 * jnp.sum(
                var1 / var2 + ((m2 - m1) ** 2) / var2 - 1.0
                + jnp.log(var2) - jnp.log(var1), axis=-1)
        return apply(_kl, self.loc, self.scale, other.loc, other.scale)


@register_kl(MultivariateNormalDiag, MultivariateNormalDiag)
def _kl_mvndiag_mvndiag(p, q):
    return p.kl_divergence(q)
