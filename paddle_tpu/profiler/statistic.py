"""Profiler statistics: per-op summary tables parsed from the exported
trace (reference: python/paddle/profiler/profiler_statistic.py — the
1,648-line statistic builder over the reference's host/device event
tree).

The jax/XLA profiler already records what the reference's tracer
records — python annotations, runtime infrastructure, and the actual
device computations (XLA thunks: fusions, dot_general, reductions,
collectives). This module parses the chrome-trace JSON the profiler
exports (``<host>.trace.json.gz`` under
``<dir>/plugins/profile/<run>/``) and aggregates it into the
reference's summary shapes: overview, operator summary (calls /
total / avg / max / min per op), and a user-annotation (RecordEvent)
summary. ``load_profiler_result`` returns a ``ProfilerResult`` whose
tables ``Profiler.summary()`` prints.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Dict, List, Optional

# runtime plumbing, not computation: filtered out of the operator table
_INFRA_PREFIXES = (
    "PjRt", "PjitFunction", "PythonRefManager", "ParseArguments",
    "Handle inputs", "ThreadpoolListener", "CommonPjRt", "Wait for",
    "ThunkExecutor", "CollectGarbage", "process_", "thread_",
    "BufferFromHostBuffer", "CopyToDevice", "TransferTo", "XlaComputation",
    "end: ",
)


class EventRecord:
    __slots__ = ("name", "pid", "tid", "start_us", "dur_us", "process",
                 "kind")

    def __init__(self, name, pid, tid, start_us, dur_us, process, kind):
        self.name = name
        self.pid = pid
        self.tid = tid
        self.start_us = start_us
        self.dur_us = dur_us
        self.process = process  # e.g. "/host:CPU", "/device:TPU:0"
        self.kind = kind        # "op" | "annotation" | "infra"

    def __repr__(self):
        return (f"EventRecord({self.name!r}, {self.process}, "
                f"{self.dur_us:.1f}us)")


def _classify(name: str) -> str:
    if name.startswith("$") or name.startswith("UserDefined::"):
        return "annotation"  # python-level ranges / RecordEvent
    for p in _INFRA_PREFIXES:
        if name.startswith(p):
            return "infra"
    return "op"


class _Agg:
    __slots__ = ("calls", "total", "mx", "mn")

    def __init__(self):
        self.calls = 0
        self.total = 0.0
        self.mx = 0.0
        self.mn = float("inf")

    def add(self, dur):
        self.calls += 1
        self.total += dur
        self.mx = max(self.mx, dur)
        self.mn = min(self.mn, dur)

    @property
    def avg(self):
        return self.total / self.calls if self.calls else 0.0


class ProfilerResult:
    """Parsed trace: events plus the reference's aggregate views."""

    def __init__(self, events: List[EventRecord], source: str = ""):
        self.events = events
        self.source = source

    # -- construction -------------------------------------------------------

    @classmethod
    def from_chrome_trace(cls, path: str) -> "ProfilerResult":
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rt") as fh:
            doc = json.load(fh)
        processes: Dict[int, str] = {}
        for e in doc.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                processes[e["pid"]] = e.get("args", {}).get("name", "")
        events = []
        for e in doc.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            name = e.get("name", "")
            events.append(EventRecord(
                name=name, pid=e.get("pid"), tid=e.get("tid"),
                start_us=float(e.get("ts", 0.0)),
                dur_us=float(e.get("dur", 0.0)),
                process=processes.get(e.get("pid"), ""),
                kind=_classify(name)))
        return cls(events, source=path)

    @classmethod
    def from_trace_dir(cls, dir_name: str) -> "ProfilerResult":
        pats = [os.path.join(dir_name, "plugins", "profile", "*",
                             "*.trace.json.gz"),
                os.path.join(dir_name, "plugins", "profile", "*",
                             "*.trace.json"),
                os.path.join(dir_name, "*.trace.json.gz"),
                os.path.join(dir_name, "*.json.gz"),
                os.path.join(dir_name, "*.json")]
        for pat in pats:
            hits = sorted(glob.glob(pat))
            if hits:
                return cls.from_chrome_trace(hits[-1])  # latest run
        raise FileNotFoundError(
            f"no chrome trace found under {dir_name!r} (expected "
            "plugins/profile/<run>/<host>.trace.json.gz — did the "
            "profiler record at least one step?)")

    # -- aggregate views ----------------------------------------------------

    def _aggregate(self, kind: str) -> Dict[str, _Agg]:
        out: Dict[str, _Agg] = {}
        for ev in self.events:
            if ev.kind != kind:
                continue
            out.setdefault(ev.name, _Agg()).add(ev.dur_us)
        return out

    def op_summary(self) -> Dict[str, dict]:
        """name -> {calls,total,avg,max,min} (microseconds) for device /
        XLA computation events — the reference's Operator Summary."""
        return {k: {"calls": a.calls, "total": a.total, "avg": a.avg,
                    "max": a.mx, "min": a.mn}
                for k, a in self._aggregate("op").items()}

    def annotation_summary(self) -> Dict[str, dict]:
        """User RecordEvent / python ranges — reference's
        UserDefined/Forward/... event-type rollup."""
        return {k: {"calls": a.calls, "total": a.total, "avg": a.avg,
                    "max": a.mx, "min": a.mn}
                for k, a in self._aggregate("annotation").items()}

    def device_summary(self) -> Dict[str, float]:
        """process name -> busy microseconds of op events."""
        out: Dict[str, float] = {}
        for ev in self.events:
            if ev.kind == "op":
                out[ev.process] = out.get(ev.process, 0.0) + ev.dur_us
        return out

    def time_range(self) -> float:
        xs = [e for e in self.events if e.dur_us > 0]
        if not xs:
            return 0.0
        lo = min(e.start_us for e in xs)
        hi = max(e.start_us + e.dur_us for e in xs)
        return hi - lo


_UNIT_DIV = {"s": 1e6, "ms": 1e3, "us": 1.0, "ns": 1e-3}

_SORT_FIELD = {  # SortedKeys -> aggregate field
    "CPUTotal": "total", "CPUAvg": "avg", "CPUMax": "max", "CPUMin": "min",
    "GPUTotal": "total", "GPUAvg": "avg", "GPUMax": "max", "GPUMin": "min",
}


def _fmt_table(title: str, rows: List[tuple], unit: str) -> str:
    div = _UNIT_DIV.get(unit, 1e3)
    header = (f"{'Name':<44} {'Calls':>6} {f'Total({unit})':>12} "
              f"{f'Avg({unit})':>10} {f'Max({unit})':>10} "
              f"{f'Min({unit})':>10}")
    bar = "-" * len(header)
    lines = [bar, title, bar, header, bar]
    for name, st in rows:
        nm = name if len(name) <= 43 else name[:40] + "..."
        lines.append(
            f"{nm:<44} {st['calls']:>6} {st['total'] / div:>12.3f} "
            f"{st['avg'] / div:>10.3f} {st['max'] / div:>10.3f} "
            f"{st['min'] / div:>10.3f}")
    lines.append(bar)
    return "\n".join(lines)


def build_summary(result: ProfilerResult, sorted_by=None,
                  time_unit: str = "ms") -> str:
    """Format the reference's summary tables from a parsed trace
    (profiler_statistic.py _build_table analog)."""
    field = _SORT_FIELD.get(
        getattr(sorted_by, "name", str(sorted_by)), "total")
    parts = []
    dev = result.device_summary()
    if dev:
        div = _UNIT_DIV.get(time_unit, 1e3)
        span = result.time_range() / div
        lines = ["Device Summary:"]
        for proc, busy in sorted(dev.items()):
            lines.append(f"  {proc or '<unknown>'}: busy "
                         f"{busy / div:.3f}{time_unit} over a "
                         f"{span:.3f}{time_unit} span")
        parts.append("\n".join(lines))
    ops = sorted(result.op_summary().items(),
                 key=lambda kv: kv[1][field], reverse=True)
    if ops:
        parts.append(_fmt_table("Operator Summary "
                                f"(sorted by {field})", ops, time_unit))
    anns = sorted(result.annotation_summary().items(),
                  key=lambda kv: kv[1]["total"], reverse=True)
    if anns:
        parts.append(_fmt_table("UserDefined / Python Summary",
                                anns[:20], time_unit))
    return "\n\n".join(parts) if parts else "no events parsed"


def load_profiler_result(filename: str) -> ProfilerResult:
    """Load an exported trace — a profiler output dir, a
    plugins/profile run dir, or a chrome-trace json(.gz) file
    (reference profiler.py:load_profiler_result)."""
    if os.path.isdir(filename):
        return ProfilerResult.from_trace_dir(filename)
    if not os.path.exists(filename):
        raise FileNotFoundError(
            f"no chrome trace at {filename!r} (pass the profiler's "
            "output dir or a *.trace.json[.gz] file)")
    return ProfilerResult.from_chrome_trace(filename)
