"""Compiled 1F1B pipeline train step.

Reference: fleet/meta_parallel/pipeline_parallel.py — PipelineParallel
.train_batch runs forward_backward_pipeline (1F1B) then the optimizer
update. TPU-native: ONE jit program — embedding vjp outside the ring,
ops.pipeline.pipeline_1f1b (fused fwd+bwd schedule, O(P) activation
memory) over the decoder stack with final-norm/head/loss inside the last
stage, then the functional optimizer update on donated buffers.

Model contract: ``model.pipeline_parts()`` returning
(embed_params, stacked_params, last_params, embed_fn, stage_fn, last_fn) —
see text/models/llama_pipe.LlamaForCausalLMPipe.pipeline_parts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .train_step import CompiledTrainStep


class Compiled1F1BTrainStep(CompiledTrainStep):
    """CompiledTrainStep whose gradients come from the 1F1B schedule
    instead of whole-program AD (which would GPipe-shape the backward and
    hold O(n_micro) activations)."""

    def __init__(self, model, optimizer, n_micro=None, strategy=None,
                 donate=True):
        self.n_micro = n_micro
        (self._embed_p, self._stacked_p, self._last_p, self._embed_fn,
         self._stage_fn, self._last_fn) = model.pipeline_parts()
        super().__init__(model, optimizer,
                         loss_fn=lambda m, i, l: (_ for _ in ()).throw(
                             RuntimeError("1F1B step owns the loss")),
                         strategy=strategy, donate=donate)

    def _step(self, param_vals, opt_state, buffer_vals, scaler_state, batch,
              key, lr):
        from ...ops.pipeline import pipeline_1f1b

        from ...tensor import Tensor

        ids, labels = (b._data if isinstance(b, Tensor) else b
                       for b in batch)
        embed_vals = {k: param_vals[k] for k in self._embed_p}
        stacked_vals = {k: param_vals[k] for k in self._stacked_p}
        last_vals = {k: param_vals[k] for k in self._last_p}

        x, embed_vjp = jax.vjp(
            lambda ev: self._embed_fn(ev, ids), embed_vals)

        loss, g_stack, g_last, dx = pipeline_1f1b(
            self._stage_fn, self._last_fn, stacked_vals, x, labels,
            last_params=last_vals, mesh=self._mesh, n_micro=self.n_micro)
        (g_embed,) = embed_vjp(dx.astype(x.dtype))

        grads = {}
        for src in (g_stack, g_last, g_embed):
            for k, g in src.items():
                grads[k] = g.astype(param_vals[k].dtype)

        new_params, new_opt = self.optimizer.apply_gradients_functional(
            param_vals, grads, opt_state, lr, params_ref=self._params)
        return (loss, new_params, new_opt, buffer_vals, scaler_state,
                jnp.asarray(False))


def make_1f1b_train_step(model, optimizer, n_micro=None,
                         strategy=None) -> Compiled1F1BTrainStep:
    return Compiled1F1BTrainStep(model, optimizer, n_micro=n_micro,
                                 strategy=strategy)
