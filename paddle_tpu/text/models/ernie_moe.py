"""ERNIE-MoE style expert-parallel transformer (baseline config 5).

Reference pairing: python/paddle/incubate/distributed/models/moe (c_alltoall
dispatch). Built on paddle_tpu.nn.moe.MoELayer — the expert axis shards on
the mesh model-parallel ("tp") axis — the reference's EP — and XLA emits the all-to-all.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ...nn import Dropout, Embedding, LayerNorm, Linear, MoELayer
from ...nn import functional as F
from ...nn.layer_base import Layer
from ...nn.layer.container import LayerList
from ...tensor import Tensor
from ...tensor_ops.manipulation import reshape, split


@dataclass
class ErnieMoEConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    num_experts: int = 8
    moe_every: int = 2  # every Nth layer is MoE
    top_k: int = 2
    max_position_embeddings: int = 512
    dropout: float = 0.1
    aux_loss_weight: float = 0.01


ERNIE_MOE_TINY = ErnieMoEConfig(vocab_size=1024, hidden_size=128,
                                num_hidden_layers=2, num_attention_heads=4,
                                intermediate_size=256, num_experts=4,
                                max_position_embeddings=128)


class MoEBlock(Layer):
    def __init__(self, c: ErnieMoEConfig, use_moe: bool):
        super().__init__()
        self.ln_1 = LayerNorm(c.hidden_size)
        self.num_heads = c.num_attention_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.qkv = Linear(c.hidden_size, 3 * c.hidden_size)
        self.proj = Linear(c.hidden_size, c.hidden_size)
        self.ln_2 = LayerNorm(c.hidden_size)
        self.use_moe = use_moe
        if use_moe:
            self.moe = MoELayer(c.hidden_size, c.intermediate_size,
                                c.num_experts, k=c.top_k)
        else:
            self.fc1 = Linear(c.hidden_size, c.intermediate_size)
            self.fc2 = Linear(c.intermediate_size, c.hidden_size)
        self.drop = Dropout(c.dropout)

    def forward(self, x):
        b, l, h = x.shape
        q, k, v = split(self.qkv(self.ln_1(x)), 3, axis=-1)
        q = reshape(q, (b, l, self.num_heads, self.head_dim))
        k = reshape(k, (b, l, self.num_heads, self.head_dim))
        v = reshape(v, (b, l, self.num_heads, self.head_dim))
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=False)
        x = x + self.drop(self.proj(reshape(attn, (b, l, h))))
        y = self.ln_2(x)
        if self.use_moe:
            x = x + self.drop(self.moe(y))
        else:
            x = x + self.drop(self.fc2(F.gelu(self.fc1(y))))
        return x


class ErnieMoEModel(Layer):
    def __init__(self, config: ErnieMoEConfig = ErnieMoEConfig()):
        super().__init__()
        self.config = config
        self.word_emb = Embedding(config.vocab_size, config.hidden_size)
        self.pos_emb = Embedding(config.max_position_embeddings,
                                 config.hidden_size)
        self.blocks = LayerList([
            MoEBlock(config, use_moe=(i % config.moe_every == config.moe_every - 1))
            for i in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size)

    def forward(self, input_ids):
        l = input_ids.shape[1]
        pos = Tensor(jnp.arange(l, dtype=jnp.int32)[None, :])
        x = self.word_emb(input_ids) + self.pos_emb(pos)
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)

    def aux_loss(self):
        total = None
        for blk in self.blocks:
            if blk.use_moe and blk.moe.aux_loss is not None:
                total = blk.moe.aux_loss if total is None else total + blk.moe.aux_loss
        return total


class ErnieMoEForPretraining(Layer):
    def __init__(self, config: ErnieMoEConfig = ErnieMoEConfig()):
        super().__init__()
        self.config = config
        self.ernie = ErnieMoEModel(config)
        self.lm_head = Linear(config.hidden_size, config.vocab_size,
                              bias_attr=False)

    def forward(self, input_ids, labels=None):
        hidden = self.ernie(input_ids)
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = F.cross_entropy(
                reshape(logits, (-1, self.config.vocab_size)).astype("float32"),
                reshape(labels, (-1,)), ignore_index=-100)
            aux = self.ernie.aux_loss()
            if aux is not None:
                loss = loss + self.config.aux_loss_weight * aux
            return loss
        return logits
