"""Reference spelling: python/paddle/utils/install_check.py (run_check).

The implementation (a tiny matmul on the default backend plus an 8-virtual
-device sharded matmul when multiple devices are visible) lives in
utils/__init__.py.
"""
from . import run_check

__all__ = ["run_check"]
