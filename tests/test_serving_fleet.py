"""Replica fleet (paddle_tpu.serving.fleet).

The headline contract: kill / wedge / KV-corrupt ONE of N replicas
mid-decode and every in-flight request still completes with output
TOKEN-IDENTICAL to an uninterrupted single-engine baseline — the
faulted replica's requests fail over to healthy peers via ``adopt()``
(PRNG-chain fast-forward) while it drains, rebuilds, and re-registers,
and zero requests are lost. Prefix-aware routing, jittered backoff
honoring retry_after_s, fleet-wide-vs-per-replica brownout, the adopt
fingerprint guard, audit_fleet budgeting and the metrics/profiler
surface ride along.

Kept slim for the tier-1 budget: one module-scope tiny model with the
same geometry/statics as test_serving_resilience.py so the module-level
jit programs are shared across test modules; the chaos soak and the
mixed-tp sweep are marked slow.
"""
import dataclasses
import os
import sys
import time

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu.resilience import FLEET_FAULTS, ChaosMonkey
from paddle_tpu.serving import (AdoptMismatch, Engine, EngineDraining,
                                EngineOverloaded, ReplicaFleet,
                                RequestShed)
from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = dataclasses.replace(LLAMA_TINY, dtype="float32", num_hidden_layers=2)

GREEDY = dict(n_slots=2, max_len=64, min_prompt_bucket=4, block_size=8)
SAMPLED = dict(do_sample=True, top_k=8, **GREEDY)

needs2 = pytest.mark.skipif(len(jax.devices()) < 2,
                            reason="needs >= 2 virtual devices")


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = LlamaForCausalLM(CFG)
    m.eval()
    return m


def _prompts(lens, seed, shared_prefix=0):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, CFG.vocab_size,
                          (shared_prefix,)).astype(np.int32)
    out = []
    for n in lens:
        tail = rng.integers(0, CFG.vocab_size, (int(n),)).astype(np.int32)
        out.append(np.concatenate([prefix, tail]) if shared_prefix
                   else tail)
    return out


def _staggered(server, prompts, gen):
    """Same staggered schedule against an Engine or a ReplicaFleet: ≥3
    requests at different decode positions when a mid-run fault fires."""
    handles = []
    handles.append(server.submit(prompts[0], **gen[0]))
    server.step()
    server.step()
    handles.append(server.submit(prompts[1], **gen[1]))
    server.step()
    handles.append(server.submit(prompts[2], **gen[2]))
    handles.append(server.submit(prompts[3], **gen[3]))
    while any(not h.finished for h in handles):
        server.step()
    return handles


_GEN = [dict(max_new_tokens=6, temperature=0.8, seed=11),
        dict(max_new_tokens=6, temperature=1.2, seed=7),
        dict(max_new_tokens=5, temperature=0.6, seed=3),
        dict(max_new_tokens=4, temperature=1.0, seed=23)]


# ---------------------------------------------------------------------------
# headline: one replica faulted mid-decode -> cross-replica migration,
# zero lost, token-identical to the single-engine baseline
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fault", ["replica-kill", "decode-stall",
                                   "kv-corrupt"])
def test_replica_fault_migrates_token_identical(model, fault):
    prompts = _prompts([3, 4, 5, 6], seed=1, shared_prefix=8)
    base = _staggered(Engine(model, **SAMPLED), prompts, _GEN)
    want = [list(h.tokens) for h in base]

    chaos = ChaosMonkey(seed=0, at={4: fault}, stall_s=0.01)
    fleet = ReplicaFleet(model, 3, chaos=chaos, kv_probe_interval=1,
                         **SAMPLED)
    got = _staggered(fleet, prompts, _GEN)
    assert [list(h.tokens) for h in got] == want
    assert all(h.finish_reason == "length" for h in got)   # zero lost
    assert chaos.fired == [(4, fault)]
    assert fleet.migrations >= 1          # in-flight work moved to peers
    # the faulted replica drained, rebuilt, and re-registered
    assert fleet.re_registers == 1
    assert all(s == "healthy" for s in fleet.replica_states().values())
    counts = fleet.ledger.counts()
    assert counts["migrate"] == fleet.migrations
    assert counts["re-register"] == 1
    if fault == "replica-kill":
        assert fleet.replica_kills == 1
    # pool hygiene on every replica after the fault + migration
    assert all(r.engine.cache.check_refcounts()
               for r in fleet.replicas.values())


def test_migration_keeps_trace_id_and_replica_tag(model):
    """A migrated handle keeps its lifecycle trace id (the PR-9
    contract, now across REPLICAS) and its replica_id follows it to the
    adopting peer; the fleet ledger's migrate record links both."""
    prompts = _prompts([3, 4, 5, 6], seed=2, shared_prefix=8)
    chaos = ChaosMonkey(seed=0, at={4: "replica-kill"})
    fleet = ReplicaFleet(model, 3, chaos=chaos, **GREEDY)
    handles = []
    handles.append(fleet.submit(prompts[0], **_GEN[0]))
    fleet.step()
    fleet.step()
    handles.append(fleet.submit(prompts[1], **_GEN[1]))
    origins = {h.request_id: (h.trace_id, h.replica_id) for h in handles}
    fleet.step()
    fleet.step()      # step 3 ... chaos fires at fleet step 4
    fleet.step()
    assert fleet.replica_kills == 1 and fleet.migrations >= 1
    migrated = [r for r in fleet.ledger.to_list()
                if r["event"] == "migrate"]
    assert migrated
    for rec in migrated:
        h = next(x for x in handles if x.request_id == rec["request_id"])
        assert h.trace_id == rec["trace_id"]          # id survived
        assert origins[h.request_id][0] == h.trace_id
        assert rec["target"] == h.replica_id          # tag follows
        assert rec["source"] != rec["target"]
    while any(not h.finished for h in handles):
        fleet.step()
    assert all(h.finish_reason == "length" for h in handles)


# ---------------------------------------------------------------------------
# prefix-aware routing
# ---------------------------------------------------------------------------

def test_routing_prefers_prefix_holding_replica(model):
    """A request whose prompt shares a full-block prefix with one
    already served routes to THE replica whose radix holds it; a
    prefix-less request balances to the least-loaded replica instead."""
    fleet = ReplicaFleet(model, 3, **GREEDY)
    shared = _prompts([3], seed=3, shared_prefix=8)[0]
    h0 = fleet.submit(shared, max_new_tokens=2)
    while not h0.finished:
        fleet.step()
    holder = h0.replica_id
    # the prefix (one full 8-token block) is committed on `holder` only
    h1 = fleet.submit(
        np.concatenate([shared[:8],
                        _prompts([4], seed=4)[0]]), max_new_tokens=2)
    assert h1.replica_id == holder
    assert fleet.prefix_routed == 1
    route = [r for r in fleet.ledger.to_list() if r["event"] == "route"]
    assert route[-1]["prefix_tokens"] == 8
    # no prefix anywhere: load balances AWAY from the busy holder
    h2 = fleet.submit(_prompts([5], seed=5)[0], max_new_tokens=2)
    assert h2.replica_id != holder
    while not (h1.finished and h2.finished):
        fleet.step()
    # the routing probe is read-only: refcounts/radix untouched
    assert all(r.engine.cache.check_refcounts()
               for r in fleet.replicas.values())


def test_route_flap_does_not_change_tokens(model):
    """Chaos route-flap randomizes placement; per-request PRNG chains
    make tokens placement-independent, so output still matches the
    single-engine baseline exactly."""
    prompts = _prompts([3, 4, 5, 6], seed=6, shared_prefix=8)
    want = [list(h.tokens)
            for h in _staggered(Engine(model, **SAMPLED), prompts, _GEN)]
    chaos = ChaosMonkey(seed=1, at={0: "route-flap"})
    fleet = ReplicaFleet(model, 3, chaos=chaos, **SAMPLED)
    got = _staggered(fleet, prompts, _GEN)
    assert fleet.route_flaps == 1
    assert [list(h.tokens) for h in got] == want


# ---------------------------------------------------------------------------
# brownout: one replica reroutes, ALL replicas shed fleet-wide
# ---------------------------------------------------------------------------

def test_one_browned_replica_reroutes_all_browned_sheds_fleet_wide(model):
    prompts = _prompts([5, 5, 5, 5], seed=7)
    fleet = ReplicaFleet(model, 2, n_slots=1, max_len=64,
                         min_prompt_bucket=4, itl_slo_ms=50.0)
    reps = list(fleet.replicas.values())
    # occupy both replicas and queue one unprotected request on each
    hogs = [fleet.submit(prompts[i], max_new_tokens=8, priority=0)
            for i in range(2)]
    lows = [fleet.submit(prompts[2 + i], max_new_tokens=4, priority=5)
            for i in range(2)]
    assert {h.replica_id for h in hogs} == {"r0", "r1"}
    # ONE replica over its SLO: unprotected admission just routes to the
    # healthy peer — nothing is shed fleet-wide
    for _ in range(8):
        reps[0].engine.metrics.mark_decode(0.5)
    fleet.step()
    assert reps[0].sup._brownout and not reps[1].sup._brownout
    assert fleet.replica_states()["r0"] == "degraded"
    h = fleet.submit(prompts[0], max_new_tokens=2, priority=5)
    assert h.replica_id == "r1"
    assert fleet.fleet_sheds == 0
    fleet.cancel(h)
    # BOTH replicas browned out: unprotected admission is rejected
    # fleet-wide with a finite hint, and the lowest queued class is shed
    # on EVERY replica
    for _ in range(8):
        reps[1].engine.metrics.mark_decode(0.5)
    fleet.step()
    assert all(r.sup._brownout for r in reps)
    with pytest.raises(EngineOverloaded) as ei:
        fleet.submit(prompts[0], max_new_tokens=2, priority=5)
    assert ei.value.replica is None           # fleet-wide, not one replica
    assert ei.value.retry_after_s is not None \
        and np.isfinite(ei.value.retry_after_s)
    still_queued = [h for h in lows if not h.finished]
    assert not still_queued or fleet.fleet_sheds >= 1
    shed = [h for h in lows if h.finish_reason == "shed"]
    assert shed
    with pytest.raises(RequestShed) as si:
        shed[0].result()
    assert si.value.replica == shed[0].replica_id is not None
    # protected class still admits during the fleet brownout
    hp = fleet.submit(prompts[1], max_new_tokens=2, priority=0)
    assert hp.replica_id is not None
    # recovery: p95 back under SLO on both -> healthy again
    for r in reps:
        for _ in range(64):
            r.engine.metrics.mark_decode(0.001)
    fleet.step()
    assert all(s == "healthy" for s in fleet.replica_states().values())
    fleet.drain()


def test_backoff_honors_retry_after(model):
    """A replica that rejects enters a jittered backoff window scaled
    by its retry_after_s: the router skips it while the window holds
    and returns to it after it elapses."""
    p = _prompts([5], seed=8)[0]
    fleet = ReplicaFleet(model, 2, n_slots=1, max_len=64,
                         min_prompt_bucket=4, max_queue=1, seed=3,
                         default_retry_after_s=0.05)
    # fill r0 (slot + queue) so its next enqueue raises EngineOverloaded
    h0 = fleet.submit(p, max_new_tokens=6)
    h1 = fleet.submit(p, max_new_tokens=2)
    first = h0.replica_id
    assert h1.replica_id != first      # load-balanced, not backoff yet
    h2 = fleet.submit(p, max_new_tokens=2)     # queues on one of them
    h3 = fleet.submit(p, max_new_tokens=2)     # queues on the other
    assert fleet.backoffs == 0
    # both queues full now: the next submit hits a backoff on one
    # replica, retries the peer, and ultimately raises fleet-wide
    with pytest.raises(EngineOverloaded) as ei:
        fleet.submit(p, max_new_tokens=2)
    assert ei.value.replica is None
    assert fleet.backoffs >= 1 and fleet.retries >= 1
    # the window honors retry_after_s: deadline within (0.5, 1.0] x hint
    now = time.monotonic()
    for rid, until in fleet._backoff_until.items():
        assert until <= now + 0.05 + 1e-3
        assert until > now - 0.05
    rec = [r for r in fleet.ledger.to_list() if r["event"] == "backoff"]
    assert rec and rec[0]["retry_after_s"] is not None
    time.sleep(0.06)                   # window elapses -> routable again
    fleet.drain()
    fleet.reopen()
    h4 = fleet.submit(p, max_new_tokens=2)
    assert h4.replica_id is not None
    h4.result()


# ---------------------------------------------------------------------------
# drain / re-register / kill API
# ---------------------------------------------------------------------------

def test_kill_drain_reregister_and_fleet_drain(model):
    prompts = _prompts([5, 5], seed=9)
    fleet = ReplicaFleet(model, 2, cooldown_steps=3, **GREEDY)
    h0 = fleet.submit(prompts[0], max_new_tokens=6)
    victim = h0.replica_id
    moved = fleet.kill_replica(victim)
    assert moved == 1 and h0.replica_id != victim
    assert fleet.replica_states()[victim] == "draining"
    # draining replicas take no traffic
    h1 = fleet.submit(prompts[1], max_new_tokens=2)
    assert h1.replica_id != victim
    for _ in range(3):
        assert fleet.replica_states()[victim] == "draining"
        fleet.step()
    assert fleet.replica_states()[victim] == "healthy"
    assert fleet.re_registers == 1
    # fleet drain: everything finishes, admission closes, reopen works
    report = fleet.drain()
    assert report["drained"] and h0.finished and h1.finished
    assert h0.finish_reason == "length"
    with pytest.raises(EngineDraining):
        fleet.submit(prompts[0], max_new_tokens=2)
    fleet.reopen()
    fleet.submit(prompts[0], max_new_tokens=2).result()


# ---------------------------------------------------------------------------
# adopt() fingerprint guard (satellite bugfix)
# ---------------------------------------------------------------------------

def test_adopt_guard_rejects_mismatched_model(model):
    """adopt() refuses a handle from an engine over a DIFFERENT
    model/config instead of silently producing divergent tokens; a
    same-model engine (the migration case) adopts fine."""
    paddle.seed(1)
    other = LlamaForCausalLM(
        dataclasses.replace(CFG, num_hidden_layers=1))
    other.eval()
    p = _prompts([5], seed=10)[0]
    a = Engine(model, **GREEDY)
    h = a.submit(p, max_new_tokens=6)
    a.step()
    a._condemned = True
    b = Engine(other, **GREEDY)
    with pytest.raises(AdoptMismatch, match="fingerprint"):
        b.adopt(h)
    # sampling statics are part of the fingerprint too: a do_sample
    # engine must not adopt a greedy handle (different baked programs)
    c = Engine(model, **SAMPLED)
    with pytest.raises(AdoptMismatch):
        c.adopt(h)
    # the legitimate path: same model + statics, fresh engine
    d = Engine(model, **GREEDY)
    d.adopt(h)
    base = Engine(model, **GREEDY).generate_all(
        [p], max_new_tokens=6, seed=h.seed)[0]
    assert list(h.result()[len(p):]) == list(base.tokens)


# ---------------------------------------------------------------------------
# analysis / metrics / profiler surface
# ---------------------------------------------------------------------------

def test_audit_fleet_budgets_union_across_replicas(model):
    from paddle_tpu import analysis

    chaos = ChaosMonkey(seed=0, at={3: "decode-raise"})
    fleet = ReplicaFleet(model, 3, chaos=chaos, compile_budget=2,
                         **GREEDY)
    hs = [fleet.submit(_prompts([5], seed=11)[0], max_new_tokens=4)
          for _ in range(3)]
    for _ in range(2):
        fleet.step()
    while any(not h.finished for h in hs):
        fleet.step()
    rep = analysis.audit_fleet(fleet)
    m = rep.metrics["compile-budget"]
    # 3 replicas + a mid-run rebuild, ONE engine's program set
    assert m["prefill_buckets"] == [8] and m["programs"] == 2
    assert not [f for f in rep.findings
                if f.rule_id == "compile-budget" and f.severity == "high"]
    assert rep.metrics["fleet"]["n_replicas"] == 3
    over = analysis.audit_fleet(fleet, compile_budget=1)
    assert [f for f in over.findings
            if f.rule_id == "compile-budget" and f.severity == "high"]


def test_fleet_metrics_registry_and_profiler_line(model, capsys):
    import paddle_tpu.profiler as profiler
    from paddle_tpu import observability as obs

    chaos = ChaosMonkey(seed=0, at={2: "replica-kill"})
    fleet = ReplicaFleet(model, 2, chaos=chaos, **GREEDY)
    h = fleet.submit(_prompts([5], seed=12)[0], max_new_tokens=6)
    h.result()
    c = profiler.fleet_counters()
    assert c["fleets"] >= 1 and c["replica_kills"] >= 1
    snap = obs.metrics.snapshot()
    states = snap["paddle_serving_replica_state"]["samples"]
    ours = [s for s in states
            if s["labels"].get("fleet") == fleet.name]
    assert {s["labels"]["replica"] for s in ours} == {"r0", "r1"}
    assert all(s["value"] in (0.0, 1.0, 2.0, 3.0) for s in ours)
    kinds = {s["labels"]["kind"]: s["value"] for s in
             snap["paddle_serving_fleet_events_total"]["samples"]}
    for k in ("routed", "prefix_routed", "migrations", "failovers",
              "replica_kills", "route_flaps", "fleet_sheds", "backoffs"):
        assert k in kinds
    assert "paddle_serving_replica_state" in obs.metrics.to_prometheus()
    # fleet-scope flight ledgers export separately from train/serving
    assert snap["paddle_resilience_fleet_ledgers"]["samples"][0][
        "value"] >= 1
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    prof.step()
    prof.stop()
    prof.summary()
    out = capsys.readouterr().out
    assert "fleet:" in out and "migrations=" in out


def test_fleet_validation(model):
    with pytest.raises(ValueError, match="n_replicas"):
        ReplicaFleet(model, 0)
    with pytest.raises(ValueError, match="tp_degrees"):
        ReplicaFleet(model, 2, tp_degrees=[1])
    with pytest.raises(ValueError):
        ChaosMonkey(at={1: "replica-explode"})
    assert set(FLEET_FAULTS) >= {"replica-kill", "route-flap"}


# ---------------------------------------------------------------------------
# chaos_serve --fleet CLI smoke (the tier-1 wiring)
# ---------------------------------------------------------------------------

def test_chaos_serve_fleet_cli_smoke(capsys):
    import json

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_serve
    finally:
        sys.path.pop(0)
    rc = chaos_serve.main(["--fleet", "3", "--fault", "kill", "--json"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and rec["ok"]
    assert rec["token_identical"] and rec["zero_lost"]
    for arm in ("greedy", "sampled"):
        a = rec["arms"][arm]
        assert a["replica_kills"] == 1 and a["migrations"] >= 1
        assert a["fired"] == [[4, "replica-kill"]] \
            or a["fired"] == [(4, "replica-kill")]


# ---------------------------------------------------------------------------
# slow: seeded chaos soak + mixed-tp fleet
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_fleet_chaos_sweep(model):
    """Seeded chaos over every fleet fault with random arrivals: all
    requests finish token-identically to the uninterrupted baseline."""
    rng = np.random.default_rng(13)
    reqs = [(rng.integers(0, CFG.vocab_size, (int(n),)).astype(np.int32),
             int(m), int(s))
            for n, m, s in zip(rng.integers(4, 13, 16),
                               rng.integers(2, 8, 16),
                               rng.integers(0, 1 << 30, 16))]

    def run(server):
        handles = []
        for i, (p, m, s) in enumerate(reqs):
            handles.append(server.submit(p, max_new_tokens=m, seed=s,
                                         temperature=0.9))
            for _ in range(int(i % 3)):
                server.step()
        while any(not h.finished for h in handles):
            server.step()
        return handles

    want = [list(h.tokens) for h in run(Engine(model, **SAMPLED))]
    for seed in (1, 2, 3):
        chaos = ChaosMonkey(seed=seed, p=0.12, faults=FLEET_FAULTS,
                            stall_s=0.01, horizon=256)
        fleet = ReplicaFleet(model, 3, chaos=chaos, kv_probe_interval=1,
                             seed=seed, **SAMPLED)
        got = run(fleet)
        for i, h in enumerate(got):
            assert list(h.tokens) == want[i], (seed, i, chaos.fired)
        assert fleet.n_pending == 0
        assert all(r.engine.cache.check_refcounts()
                   for r in fleet.replicas.values())


@pytest.mark.slow
@needs2
def test_mixed_tp_fleet_migration_parity(model):
    """Mixed tp degrees in one fleet: a tp=2 replica's in-flight
    requests migrate onto a tp=1 peer (adopt replays from tokens, not
    KV bytes) and finish token-identically to the single-device
    baseline — the tp-degree-crossing adopt parity regression."""
    prompts = _prompts([3, 4, 5, 6], seed=14, shared_prefix=8)
    want = [list(h.tokens)
            for h in _staggered(Engine(model, **SAMPLED), prompts, _GEN)]
    fleet = ReplicaFleet(model, 2, tp_degrees=[2, 1], **SAMPLED)
    tp2 = fleet.replicas["r0"]
    assert tp2.engine.tp == 2 and fleet.replicas["r1"].engine.tp == 1
    handles = []
    handles.append(fleet.submit(prompts[0], **_GEN[0]))
    fleet.step()
    fleet.step()
    handles.append(fleet.submit(prompts[1], **_GEN[1]))
    fleet.step()
    # kill whichever replica holds in-flight work; at least one handle
    # must cross a tp boundary over the two kills
    fleet.kill_replica("r0")
    handles.append(fleet.submit(prompts[2], **_GEN[2]))
    fleet.step()
    fleet.kill_replica("r1")
    handles.append(fleet.submit(prompts[3], **_GEN[3]))
    while any(not h.finished for h in handles):
        fleet.step()
    assert [list(h.tokens) for h in handles] == want
    assert fleet.migrations >= 2
    assert all(h.finish_reason == "length" for h in handles)
