"""Federated-learning coordinator (the PS stack's FL mode).

Reference: python/paddle/distributed/ps/coordinator.py:1 (FLClient pushes
state over the PS RPC wire, a coordinator-side ClientSelector picks the
round's cohort, FLStrategy strings flow back). TPU-native: no RPC — the
exchange medium is the shared filesystem every pod slice mounts (same
substrate as distributed.elastic membership): clients push numpy state
dicts into a round directory, the coordinator federated-averages the
cohort (FedAvg, weighted by example counts) and publishes the global
round; barriers are file-existence waits.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

from ...tensor import Tensor

__all__ = ["ClientInfoAttr", "FLStrategy", "ClientSelector", "Coordinator",
           "FLClient"]


class ClientInfoAttr:
    """Reference coordinator.py:35 field ids of the client info proto."""
    DEVICE_TYPE = 0
    COST_INFO = 1
    RESOURCE_INFO = 2


class FLStrategy:
    """Reference coordinator.py:42 strategy kinds."""
    JOIN = "join"
    WAIT = "wait"
    FINISH = "finish"


class ClientSelector:
    """Pick each round's cohort (reference ClientSelector.select):
    deterministic seeded sampling of a fraction of registered clients."""

    def __init__(self, fraction=1.0, seed=0):
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got {fraction}")
        self.fraction = float(fraction)
        self.seed = int(seed)

    def select(self, client_ids, round_idx):
        ids = sorted(client_ids)
        if not ids:
            return []
        k = max(1, int(round(len(ids) * self.fraction)))
        rng = np.random.default_rng((self.seed, round_idx))
        picked = rng.choice(len(ids), size=k, replace=False)
        return [ids[i] for i in sorted(picked)]


def _save_state(path, state, meta):
    """ONE atomically-replaced npz carries both arrays and meta — a
    re-publish can never hand a concurrent reader new meta with old
    weights (or vice versa)."""
    arrays = {k: np.asarray(v._data if isinstance(v, Tensor) else v)
              for k, v in state.items()}
    tmp = path + ".tmp.npz"
    np.savez(tmp, __meta__=np.asarray(json.dumps(meta)), **arrays)
    os.replace(tmp, path + ".npz")  # atomic publish


def _load_state(path):
    with np.load(path + ".npz") as z:
        meta = json.loads(str(z["__meta__"]))
        state = {k: z[k] for k in z.files if k != "__meta__"}
    return state, meta


def _wait_for(predicate, timeout, poll=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return False


class Coordinator:
    """Runs federated rounds: select cohort → wait for their pushes →
    FedAvg → publish the next global model."""

    def __init__(self, run_dir, selector: ClientSelector = None,
                 timeout=120.0, client_ttl=300.0):
        self.run_dir = os.path.abspath(run_dir)
        self.selector = selector or ClientSelector()
        self.timeout = float(timeout)
        # liveness via the elastic membership substrate: a crashed client
        # drops out of clients() after client_ttl and is never selected
        # again (reference: stale clients age out of the coordinator's
        # etcd-backed info map)
        from ..elastic import ElasticMembership
        self._members = ElasticMembership(
            os.path.join(self.run_dir, "clients"), "__coordinator__",
            timeout=client_ttl)
        os.makedirs(self.run_dir, exist_ok=True)

    def _round_dir(self, r):
        d = os.path.join(self.run_dir, f"round-{r}")
        os.makedirs(d, exist_ok=True)
        return d

    def clients(self):
        # the coordinator never register()s, so peers() is clients only
        return self._members.peers()

    def publish_global(self, r, state, cohort=None, final=False):
        d = self._round_dir(r)
        _save_state(os.path.join(d, "global"), state,
                    {"round": r, "cohort": cohort or [],
                     "strategy": (FLStrategy.FINISH if final
                                  else FLStrategy.JOIN)})

    def wait_for_clients(self, n=1, timeout=None):
        """Registration barrier: block until n clients are registered."""
        timeout = self.timeout if timeout is None else timeout
        return _wait_for(lambda: len(self.clients()) >= n, timeout)

    def run_round(self, r, global_state):
        """One federated round; returns the averaged new global state."""
        ids = self.clients()
        if not ids:
            self.wait_for_clients(1)
            ids = self.clients()  # snapshot ONCE: TTL filtering must not
            # race between the wait and the select
        if not ids:
            raise TimeoutError(
                f"round {r}: no live clients under "
                f"{self.run_dir}/clients after {self.timeout}s")
        cohort = self.selector.select(ids, r)
        self.publish_global(r, global_state, cohort)
        d = self._round_dir(r)

        def all_pushed():
            return all(os.path.exists(os.path.join(d, f"push-{c}.npz"))
                       for c in cohort)

        if not _wait_for(all_pushed, self.timeout):
            missing = [c for c in cohort if not os.path.exists(
                os.path.join(d, f"push-{c}.npz"))]
            raise TimeoutError(f"round {r}: no push from {missing}")
        states, weights = [], []
        for c in cohort:
            st, meta = _load_state(os.path.join(d, f"push-{c}"))
            states.append(st)
            weights.append(float(meta.get("examples", 1)))
        total = sum(weights)
        if total <= 0:  # all-empty cohort: fall back to unweighted mean
            weights = [1.0] * len(weights)
            total = float(len(weights))
        return {k: sum(w / total * st[k].astype(np.float64)
                       for st, w in zip(states, weights)).astype(
                           states[0][k].dtype)
                for k in states[0]}


class FLClient:
    """Client loop: register, then per round pull the global model (if
    selected), run ``train_fn`` locally, push the result (reference
    FLClient.train_loop/push_fl_client_info_sync)."""

    def __init__(self, run_dir, client_id, train_fn, timeout=120.0):
        self.run_dir = os.path.abspath(run_dir)
        self.client_id = str(client_id)
        self.train_fn = train_fn  # (round, state) -> (state, n_examples)
        self.timeout = float(timeout)
        # staleness is judged by the Coordinator's client_ttl; the
        # membership object here only writes heartbeats
        from ..elastic import ElasticMembership
        self._member = ElasticMembership(
            os.path.join(self.run_dir, "clients"),
            self.client_id).register()

    def _round_dir(self, r):
        return os.path.join(self.run_dir, f"round-{r}")

    def pull_global(self, r):
        path = os.path.join(self._round_dir(r), "global")
        if not _wait_for(lambda: os.path.exists(path + ".npz"),
                         self.timeout):
            raise TimeoutError(f"round {r}: global model never published")
        return _load_state(path)

    def run_round(self, r):
        """Returns FLStrategy for this client this round."""
        self._member.heartbeat()
        state, meta = self.pull_global(r)
        if meta.get("strategy") == FLStrategy.FINISH:
            return FLStrategy.FINISH
        if self.client_id not in meta.get("cohort", []):
            self._member.heartbeat()
            return FLStrategy.WAIT
        new_state, n_examples = self.train_fn(r, state)
        # heartbeat AFTER local training too: liveness tracks the
        # process, not the round length (a slow train_fn must not make
        # an active client read as stale)
        self._member.heartbeat()
        _save_state(os.path.join(self._round_dir(r),
                                 f"push-{self.client_id}"),
                    new_state, {"examples": int(n_examples),
                                "client": self.client_id})
        return FLStrategy.JOIN
