"""Legacy import path (reference dygraph_to_static/program_translator.py)."""
from ....jit.compat import ProgramTranslator  # noqa: F401

__all__ = ["ProgramTranslator"]
