"""incubate fused layers/optimizers, device, hub, inference, batch/reader,
cost_model."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import incubate, nn, optimizer as optim


class TestFusedLayers:
    def test_fused_mha_matches_unfused_math(self):
        """Fused MHA == manual QKV attention with the same weights."""
        paddle.seed(0)
        d, h, s = 32, 4, 16
        mha = incubate.nn.FusedMultiHeadAttention(
            d, h, dropout_rate=0.0, attn_dropout_rate=0.0,
            normalize_before=True)
        mha.eval()
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(2, s, d))
            .astype(np.float32))
        out = mha(x)
        assert list(out.shape) == [2, s, d]
        # pre-LN + residual: output differs from input, finite
        assert np.isfinite(np.asarray(out._data)).all()
        assert np.abs(np.asarray((out - x)._data)).max() > 1e-4

    def test_fused_encoder_and_multi(self):
        paddle.seed(0)
        enc = incubate.nn.FusedTransformerEncoderLayer(
            32, 4, 64, dropout_rate=0.0)
        enc.eval()
        x = paddle.to_tensor(np.ones((2, 8, 32), dtype=np.float32))
        assert list(enc(x).shape) == [2, 8, 32]
        mt = incubate.nn.FusedMultiTransformer(32, 4, 64, num_layers=2)
        mt.eval()
        assert list(mt(x).shape) == [2, 8, 32]

    def test_fused_linear_matches_linear(self):
        paddle.seed(0)
        fl = incubate.nn.FusedLinear(8, 4)
        x = paddle.to_tensor(
            np.random.default_rng(1).normal(size=(3, 8)).astype(np.float32))
        ref = x.matmul(fl.weight) + fl.bias
        np.testing.assert_allclose(np.asarray(fl(x)._data),
                                   np.asarray(ref._data), atol=1e-6)

    def test_fused_mha_cache_returns_updated_kv(self):
        paddle.seed(0)
        mha = incubate.nn.FusedMultiHeadAttention(
            16, 2, dropout_rate=0.0, attn_dropout_rate=0.0)
        mha.eval()
        x0 = paddle.to_tensor(np.ones((1, 4, 16), dtype=np.float32))
        x1 = paddle.to_tensor(np.ones((1, 1, 16), dtype=np.float32))
        from paddle_tpu.incubate.nn import functional as IF
        # prime: no cache -> single tensor
        out = mha(x0)
        assert not isinstance(out, tuple)
        # decode with a cache -> (out, (k, v)) with grown seq dim
        zeros_kv = (paddle.to_tensor(np.zeros((1, 4, 2, 8), np.float32)),
                    paddle.to_tensor(np.zeros((1, 4, 2, 8), np.float32)))
        out, cache = mha(x1, cache=zeros_kv)
        assert list(out.shape) == [1, 1, 16]
        assert list(cache[0].shape) == [1, 5, 2, 8]

    def test_fused_ffn_grad(self):
        paddle.seed(0)
        ffn = incubate.nn.FusedFeedForward(16, 32, dropout_rate=0.0)
        x = paddle.to_tensor(np.ones((2, 4, 16), dtype=np.float32))
        loss = ffn(x).sum()
        loss.backward()
        g = ffn.linear1_weight.grad
        assert g is not None and np.isfinite(np.asarray(g._data)).all()

    def test_bias_dropout_residual_ln(self):
        layer = incubate.nn.FusedBiasDropoutResidualLayerNorm(
            8, dropout_rate=0.0)
        layer.eval()
        x = paddle.to_tensor(
            np.random.default_rng(2).normal(size=(2, 3, 8))
            .astype(np.float32))
        out = np.asarray(layer(x, x)._data)
        np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)


class TestIncubateOptimizers:
    def _problem(self):
        paddle.seed(0)
        m = nn.Linear(4, 1)
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(16, 4))
            .astype(np.float32))
        w = np.array([[1.], [-2.], [0.5], [3.]], dtype=np.float32)
        y = paddle.to_tensor(np.asarray(x._data) @ w)
        return m, x, y

    def test_lookahead_converges(self):
        m, x, y = self._problem()
        la = incubate.LookAhead(
            optim.Adam(learning_rate=5e-2, parameters=m.parameters()),
            alpha=0.5, k=5)
        first = None
        for i in range(150):
            loss = ((m(x) - y) ** 2).mean()
            if first is None:
                first = float(loss._data)
            loss.backward()
            la.step()
            la.clear_grad()
        assert float(loss._data) < first * 0.1

    def test_modelaverage_apply_restore(self):
        m, x, y = self._problem()
        sgd = optim.SGD(learning_rate=1e-2, parameters=m.parameters())
        ma = incubate.ModelAverage(0.5, parameters=m.parameters(),
                                   min_average_window=2,
                                   max_average_window=100)
        for _ in range(5):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            sgd.step()
            sgd.clear_grad()
            ma.step()
        raw = np.asarray(m.weight._data).copy()
        with ma.apply():
            averaged = np.asarray(m.weight._data).copy()
        restored = np.asarray(m.weight._data)
        np.testing.assert_array_equal(restored, raw)
        assert np.abs(averaged - raw).max() > 0  # window average differs

    def test_autotune_set_config(self):
        incubate.autotune.set_config({"kernel": {"enable": False}})
        assert incubate.autotune.get_config()["kernel"]["enable"] is False
        incubate.autotune.set_config(None)
        assert incubate.autotune.get_config()["kernel"]["enable"] is True


class TestDeviceAndMisc:
    def test_device_queries(self):
        assert not paddle.is_compiled_with_cuda()
        assert paddle.device.cuda.device_count() == 0
        assert len(paddle.device.get_all_device_type()) >= 1
        assert paddle.device.cuda.synchronize() == 0

    def test_batch_and_reader(self):
        b = paddle.batch(lambda: iter(range(7)), 3)
        assert [len(x) for x in b()] == [3, 3, 1]
        b = paddle.batch(lambda: iter(range(7)), 3, drop_last=True)
        assert [len(x) for x in b()] == [3, 3]
        assert list(paddle.reader.firstn(lambda: iter(range(9)), 4)()) \
            == [0, 1, 2, 3]
        got = sorted(paddle.reader.xmap_readers(
            lambda v: v * v, lambda: iter(range(6)), 3, 4)())
        assert got == [0, 1, 4, 9, 16, 25]
        composed = paddle.reader.compose(
            lambda: iter([1, 2]), lambda: iter([(3, 4), (5, 6)]))
        assert list(composed()) == [(1, 3, 4), (2, 5, 6)]

    def test_compose_misaligned_raises(self):
        bad = paddle.reader.compose(lambda: iter([1, 2, 3]),
                                    lambda: iter([4]))
        with pytest.raises(paddle.reader.ComposeNotAligned):
            list(bad())
        ok = paddle.reader.compose(lambda: iter([1, 2, 3]),
                                   lambda: iter([4]),
                                   check_alignment=False)
        assert list(ok()) == [(1, 4), (2,), (3,)]

    def test_xmap_propagates_worker_error(self):
        def bad_mapper(v):
            if v == 3:
                raise ValueError("boom")
            return v

        r = paddle.reader.xmap_readers(bad_mapper, lambda: iter(range(6)),
                                       2, 4)
        with pytest.raises(ValueError, match="boom"):
            list(r())

    def test_hub_local(self):
        with tempfile.TemporaryDirectory() as td:
            with open(os.path.join(td, "hubconf.py"), "w") as f:
                f.write("dependencies=['numpy']\n"
                        "def tiny_model(scale=1):\n"
                        "    '''a tiny model'''\n"
                        "    return {'scale': scale}\n")
            assert paddle.hub.list(td, source="local") == ["tiny_model"]
            assert "tiny" in paddle.hub.help(td, "tiny_model",
                                             source="local")
            assert paddle.hub.load(td, "tiny_model", source="local",
                                   scale=3) == {"scale": 3}
            with pytest.raises(RuntimeError):
                paddle.hub.list(td, source="github")

    def test_cost_model(self):
        import jax.numpy as jnp
        cm = paddle.cost_model.CostModel()
        res = cm.xla_cost(lambda a, b: a @ b,
                          jnp.ones((32, 32)), jnp.ones((32, 32)))
        assert res["flops"] > 0
        timing = cm.profile_measure(lambda: jnp.ones((8, 8)).sum())
        assert timing["time"] > 0
        assert cm.profile_measure(lambda: jnp.ones(4), warmup=0,
                                  iters=2)["time"] > 0

    def test_inference_predictor(self):
        paddle.seed(0)
        layer = nn.Sequential(nn.Linear(8, 4), nn.ReLU())
        layer.eval()
        x = np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32)
        ref = np.asarray(layer(paddle.to_tensor(x))._data)
        from paddle_tpu.static import InputSpec
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m")
            paddle.jit.save(layer, path,
                            input_spec=[InputSpec([None, 8], "float32")])
            config = paddle.inference.Config(path)
            pred = paddle.inference.create_predictor(config)
            out = pred.run([x])
            np.testing.assert_allclose(out[0], ref, atol=1e-5)
            # handle-style API
            h = pred.get_input_handle(pred.get_input_names()[0])
            h.copy_from_cpu(x)
            pred.run()
            out2 = pred.get_output_handle(
                pred.get_output_names()[0]).copy_to_cpu()
            np.testing.assert_allclose(out2, ref, atol=1e-5)

    def test_inference_predictor_multi_input(self):
        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, a, b):
                return self.fc(a) + self.fc(b)

        paddle.seed(0)
        layer = TwoIn()
        layer.eval()
        a = np.ones((2, 4), dtype=np.float32)
        b = 2 * np.ones((2, 4), dtype=np.float32)
        ref = np.asarray(layer(paddle.to_tensor(a),
                               paddle.to_tensor(b))._data)
        from paddle_tpu.static import InputSpec
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m2")
            paddle.jit.save(layer, path,
                            input_spec=[InputSpec([None, 4], "float32"),
                                        InputSpec([None, 4], "float32")])
            pred = paddle.inference.create_predictor(
                paddle.inference.Config(path))
            assert pred.get_input_names() == ["x0", "x1"]
            pred.get_input_handle("x0").copy_from_cpu(a)
            pred.get_input_handle("x1").copy_from_cpu(b)
            pred.run()
            got = pred.get_output_handle(
                pred.get_output_names()[0]).copy_to_cpu()
            np.testing.assert_allclose(got, ref, atol=1e-5)


class TestNamespaceTails:
    def test_auto_checkpoint_epoch_range(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CHECKPOINT_DIR", str(tmp_path))
        from paddle_tpu.incubate import auto_checkpoint as ac

        r = ac.train_epoch_range(5, name="job1")
        seen = []
        for e in r:
            seen.append(e)
            r.save(e, {"epoch": np.asarray(e)})
            if e == 2:
                break
        # new range resumes after the last saved epoch
        r2 = ac.train_epoch_range(5, name="job1")
        assert list(r2) == [3, 4]
        restored = r2.restore({"epoch": np.asarray(0)})
        assert int(np.asarray(restored["epoch"])) == 2

    def test_layer_helper_and_asp(self):
        from paddle_tpu.incubate import LayerHelper, asp

        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            h = LayerHelper("fc", act="relu")
            w = h.create_parameter(shape=(4, 4), dtype="float32")
            assert tuple(w.shape) == (4, 4)
        assert hasattr(asp, "prune_model")

    def test_distributed_utils(self):
        from paddle_tpu.distributed import cloud_utils, utils

        name, ip = utils.get_host_name_ip()
        assert ip.count(".") == 3
        assert len(utils.find_free_ports(2)) == 2
        cluster, pod = cloud_utils.get_cluster_and_pod()
        assert cluster["world_size"] >= 1
