"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference: zkh2016/Paddle), built from scratch on
jax/XLA/pallas.

Execution model (mirrors paddle's dygraph/static split, re-designed for XLA):

* **Eager** — ops dispatch to jnp (executed async on the TPU), an autograd
  tape gives ``loss.backward()`` / ``Tensor.grad`` semantics.
* **Compiled** — ``paddle_tpu.jit.to_static`` and the train-step builders in
  hapi/fleet trace the same python code into one XLA program (grads via
  jax.grad, optimizer fused in, shardings via jax.sharding) — this is the
  performance path, equivalent to the reference's static graph + fused
  executor, with XLA doing what phi+CINN do there.
"""
from __future__ import annotations

from . import version  # noqa: F401
from .version import full_version as __version__
from .version import commit as __git_commit__  # noqa: F401

from .framework import (  # noqa: F401
    CPUPlace, CUDAPlace, TPUPlace, bfloat16, bool_, complex64, complex128,
    device_count, float16, float32, float64, get_default_dtype, get_device,
    int8, int16, int32, int64, seed, set_default_dtype, set_device, uint8,
)
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .tensor_ops import *  # noqa: F401,F403
from .tensor_ops import _bind  # noqa: F401  (attaches Tensor methods)
from .tensor_ops.creation import _memcpy  # noqa: F401  (underscore name)
from .autograd import enable_grad, grad, no_grad  # noqa: F401
from .autograd.tape import set_grad_enabled  # noqa: F401

from . import amp  # noqa: F401
from . import analysis  # noqa: F401
from . import autograd  # noqa: F401
from . import callbacks  # noqa: F401
from . import cost_model  # noqa: F401
from . import device  # noqa: F401
from . import distributed  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import geometric  # noqa: F401
from . import hub  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import observability  # noqa: F401
from . import onnx  # noqa: F401
from . import optimizer  # noqa: F401
from . import profiler  # noqa: F401
from . import reader  # noqa: F401
from . import regularizer  # noqa: F401
from . import resilience  # noqa: F401
from . import serving  # noqa: F401
from . import signal  # noqa: F401
from . import sparse  # noqa: F401
from . import static  # noqa: F401
from . import sysconfig  # noqa: F401
from . import text  # noqa: F401
from . import tuner  # noqa: F401
from . import utils  # noqa: F401
from . import vision  # noqa: F401

from .batch import batch  # noqa: F401
from .device import (  # noqa: F401
    is_compiled_with_cinn, is_compiled_with_cuda, is_compiled_with_ipu,
    is_compiled_with_mlu, is_compiled_with_npu, is_compiled_with_rocm,
    is_compiled_with_xpu,
)
from .distributed.parallel import DataParallel  # noqa: F401
from .framework.device import (  # noqa: F401
    CUDAPinnedPlace, CustomPlace, IPUPlace, MLUPlace, NPUPlace, XPUPlace,
    get_cudnn_version,
)
from .hapi.dynamic_flops import flops  # noqa: F401
from .nn.layer_base import ParamAttr  # noqa: F401

# dtype aliases matching paddle.bool / paddle.dtype
bool = bool_  # noqa: A001
import numpy as _np  # noqa: E402

dtype = _np.dtype  # paddle.dtype: dtype constructor/type


def get_cuda_rng_state():
    """No CUDA generators on the TPU stack; the jax PRNG key is the only
    device rng state (see paddle_tpu.framework.random_seed)."""
    return []


def set_cuda_rng_state(state):
    return None


def disable_signal_handler():
    return None


def check_shape(shape):
    """Validate a shape spec (ints, -1 wildcards). Reference exposes this
    as a utility in paddle.__all__."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if s is not None and not isinstance(s, int):
                raise TypeError(f"bad dim {s!r} in shape {shape!r}")
    return shape

from .framework.io import load, save  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi.model_summary import summary  # noqa: F401
from .utils.unique_name import guard as unique_name_guard  # noqa: F401

linalg = None
from . import tensor_ops as _ops  # noqa: E402
from .tensor_ops import linalg as _linalg_mod  # noqa: E402
import sys as _sys  # noqa: E402

# make `import paddle_tpu.linalg` work like the reference's real
# submodule, not just attribute access
_sys.modules[__name__ + ".linalg"] = _linalg_mod

linalg = _linalg_mod


def is_grad_enabled():
    from .autograd.tape import grad_enabled
    return grad_enabled()


def get_flags(*a, **k):
    return {}


def set_flags(*a, **k):
    return None


def in_dynamic_mode():
    from .fluid.dygraph.base import in_dygraph_mode as _idm
    from .jit.api import in_to_static
    return _idm() and not in_to_static()


def disable_static(place=None):
    from .fluid.dygraph.base import enable_dygraph
    from .static import program as _prog_mod
    from .tensor import set_op_recorder

    enable_dygraph()
    if _prog_mod._current_main is None:  # keep an active program_guard
        set_op_recorder(None)
    return None


def enable_static(place=None):
    """Reference enable_static: 1.x code then builds onto the DEFAULT
    main program (fluid.data + ops + Executor.run(default_main_program)
    without an explicit program_guard), so recording starts here."""
    from .fluid.dygraph.base import disable_dygraph
    from .static import program as _prog_mod
    from .tensor import set_op_recorder

    disable_dygraph()
    if _prog_mod._current_main is None:
        set_op_recorder(_prog_mod.default_main_program()._recorder)
    return None


from . import compat  # noqa: E402,F401

# 2.3-era `paddle.fluid` compat namespace — imported last: it aliases the
# packages above.
from . import fluid  # noqa: E402,F401

# Reference-path submodule spellings (paddle.tensor.creation,
# paddle.distribution.normal, device.cuda.streams, ...) — lazy aliases.
from . import ref_alias  # noqa: E402,F401
