"""FFT module (reference: python/paddle/fft.py) — delegates to jnp.fft."""
from __future__ import annotations

import jax.numpy as jnp

from .tensor import apply


def _fftfn(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda a: jfn(a, n=n, axis=axis, norm=norm), x)
    return op


def _fftnfn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply(lambda a: jfn(a, s=s, axes=axes, norm=norm), x)
    return op


fft = _fftfn(jnp.fft.fft)
ifft = _fftfn(jnp.fft.ifft)
rfft = _fftfn(jnp.fft.rfft)
irfft = _fftfn(jnp.fft.irfft)
hfft = _fftfn(jnp.fft.hfft)
ihfft = _fftfn(jnp.fft.ihfft)
fftn = _fftnfn(jnp.fft.fftn)
ifftn = _fftnfn(jnp.fft.ifftn)
rfftn = _fftnfn(jnp.fft.rfftn)
irfftn = _fftnfn(jnp.fft.irfftn)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.fft2(a, s=s, axes=axes, norm=norm), x)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.ifft2(a, s=s, axes=axes, norm=norm), x)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.rfft2(a, s=s, axes=axes, norm=norm), x)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.irfft2(a, s=s, axes=axes, norm=norm), x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """Hermitian 2-D FFT (reference: fft.py::hfft2): n-D inverse conjugate
    symmetry over the last axis after an inverse FFT over the first."""
    def f(a):
        n = s[-1] if s is not None else None
        inner = jnp.fft.ifft(a, n=s[0] if s else None, axis=axes[0],
                             norm=_inv_norm(norm))
        return jnp.fft.hfft(inner, n=n, axis=axes[1], norm=norm)
    return apply(f, x)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    def f(a):
        inner = jnp.fft.ihfft(a, n=s[-1] if s else None, axis=axes[1],
                              norm=norm)
        return jnp.fft.fft(inner, n=s[0] if s else None, axis=axes[0],
                           norm=_inv_norm(norm))
    return apply(f, x)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    def f(a):
        ax = axes if axes is not None else tuple(range(a.ndim))
        inner = a
        if len(ax) > 1:
            inner = jnp.fft.ifftn(
                inner, s=s[:-1] if s else None, axes=ax[:-1],
                norm=_inv_norm(norm))
        return jnp.fft.hfft(inner, n=s[-1] if s else None, axis=ax[-1],
                            norm=norm)
    return apply(f, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    def f(a):
        ax = axes if axes is not None else tuple(range(a.ndim))
        out = jnp.fft.ihfft(a, n=s[-1] if s else None, axis=ax[-1],
                            norm=norm)
        if len(ax) > 1:
            out = jnp.fft.fftn(out, s=s[:-1] if s else None, axes=ax[:-1],
                               norm=_inv_norm(norm))
        return out
    return apply(f, x)


def _inv_norm(norm):
    return {"backward": "forward", "forward": "backward",
            "ortho": "ortho"}[norm]
