"""paddle_tpu.tuner — search-based kernel autotuner (ROADMAP item 3).

CUDA-L2 / FlashFuser-style: searched kernel configs beat hand-picked
defaults, so every pallas kernel registers an enumerable config space
per ``(kernel, shape, dtype, device_kind)`` key and the tuner elects a
winner —

* **measured** on a live accelerator: min-of-batches wall time over the
  PR-9 monotonic span timer;
* **offline** on CPU: the upgraded :mod:`paddle_tpu.cost_model` ranker
  (XLA ``cost_analysis()`` base x tile-alignment / VMEM-footprint
  penalties), deterministic across processes —

and persists BOTH the winning config and its compiled executable
through the PR-10 AOT store under a toolchain-fingerprinted key, so
artifact consumers inherit tuned kernels at zero backend compiles.

Entry points::

    from paddle_tpu import tuner
    tuner.tune("ragged_matmul", args=(x, w, counts))   # search + persist
    tuner.get_config("fused_ce", shapes=..., dtype=...)  # resolve winner
    tuner.call("flash_decode", q, kc, vc, tables, wp)  # tuned + AOT-routed

Kernel call sites resolve configs through :func:`get_config`; literal
tile sizes at call sites outside this registry are flagged by the
``untuned-kernel-config`` tpu_lint rule.
"""
from __future__ import annotations

from .registry import KernelSpec, register, get as get_kernel, names  # noqa: F401
from .search import (  # noqa: F401
    TuneResult, call, clear_memory, disable, enable, enabled, get_config,
    status, tune)
from .persist import config_key, load_config, store_config  # noqa: F401

__all__ = [
    "KernelSpec", "register", "get_kernel", "names",
    "TuneResult", "tune", "get_config", "call", "status",
    "enable", "disable", "enabled", "clear_memory",
    "config_key", "load_config", "store_config",
]
