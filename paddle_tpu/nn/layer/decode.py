"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference: python/paddle/nn/decode.py (BeamSearchDecoder, dynamic_decode,
Decoder base). TPU-native notes: each decode step is static-shaped
([batch*beam, ...]); the step loop itself runs on the host because the
stop condition is data-dependent (same structure the reference uses in
dygraph mode). The backtrace is the gather_tree functional.
"""
from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor import Tensor
from ..functional.extension import gather_tree
from ..layer_base import Layer

__all__ = ['Decoder', 'BeamSearchDecoder', 'dynamic_decode']


class Decoder:
    """Reference: nn/decode.py::Decoder — initialize/step/finalize."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _map_structure(fn, obj):
    if isinstance(obj, (list, tuple)):
        return type(obj)(_map_structure(fn, o) for o in obj)
    return fn(obj)


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell. Reference:
    nn/decode.py::BeamSearchDecoder."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- beam layout helpers (reference's merge/split batch-beams) ---------
    def _merge(self, x):
        x = _raw(x)
        return x.reshape((-1,) + x.shape[2:])

    def _split(self, x, batch):
        x = _raw(x)
        return x.reshape((batch, self.beam_size) + x.shape[1:])

    def tile_beam_merge_with_batch(self, x):
        x = _raw(x)
        tiled = jnp.repeat(x[:, None], self.beam_size, axis=1)
        return tiled.reshape((-1,) + x.shape[1:])

    def initialize(self, initial_cell_states):
        cell_states = _map_structure(
            self.tile_beam_merge_with_batch, initial_cell_states)
        probe = cell_states[0] if isinstance(cell_states, (list, tuple)) \
            else cell_states
        batch = probe.shape[0] // self.beam_size
        # beam 0 starts live at log-prob 0, others at -inf
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1),
                        dtype=jnp.float32), (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), dtype=bool)
        lengths = jnp.zeros((batch, self.beam_size), dtype=jnp.int32)
        init_inputs = jnp.full((batch * self.beam_size,), self.start_token,
                               dtype=jnp.int32)
        state = self.StateWrapper(cell_states, log_probs, finished, lengths)
        return init_inputs, state, finished

    def step(self, time, inputs, states, **kwargs):
        batch = states.log_probs.shape[0]
        cell_inputs = inputs
        if self.embedding_fn is not None:
            emb = self.embedding_fn(Tensor(jnp.asarray(cell_inputs)))
            cell_inputs = _raw(emb)
        cell_out, next_cell_states = self.cell(
            Tensor(cell_inputs),
            _map_structure(Tensor, states.cell_states), **kwargs)
        logits = self.output_fn(cell_out) if self.output_fn is not None \
            else cell_out
        logits = _raw(logits)
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits, axis=-1)
        step_lp = self._split(step_lp, batch)  # [B, beam, V]

        # finished beams only extend with end_token at zero added cost
        end_mask = jax.nn.one_hot(self.end_token, vocab, dtype=bool)
        fin = states.finished[..., None]
        step_lp = jnp.where(
            fin, jnp.where(end_mask, 0.0, -1e9), step_lp)

        total = states.log_probs[..., None] + step_lp  # [B, beam, V]
        flat = total.reshape(batch, -1)
        top_scores, top_idx = jax.lax.top_k(flat, self.beam_size)
        parent = (top_idx // vocab).astype(jnp.int32)
        token = (top_idx % vocab).astype(jnp.int32)

        def pick_parent(s):
            s = self._split(s, batch)
            out = jnp.take_along_axis(
                s, parent.reshape(parent.shape + (1,) * (s.ndim - 2)),
                axis=1)
            return out.reshape((-1,) + s.shape[2:])

        next_cells = _map_structure(lambda s: pick_parent(_raw(s)),
                                    next_cell_states)
        prev_fin = jnp.take_along_axis(states.finished, parent, axis=1)
        prev_len = jnp.take_along_axis(states.lengths, parent, axis=1)
        now_fin = prev_fin | (token == self.end_token)
        lengths = jnp.where(prev_fin, prev_len, prev_len + 1)

        next_state = self.StateWrapper(next_cells, top_scores, now_fin,
                                       lengths)
        out = self.OutputWrapper(top_scores, token, parent)
        next_inputs = token.reshape(-1)
        return out, next_state, next_inputs, now_fin

    def finalize(self, outputs, final_states, sequence_lengths):
        # outputs.*: [T, B, beam] stacked — backtrace via gather_tree
        preds = gather_tree(Tensor(outputs.predicted_ids),
                            Tensor(outputs.parent_ids))
        return self.OutputWrapper(Tensor(outputs.scores), preds,
                                  Tensor(outputs.parent_ids)), final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=
                   False, impute_finished=False, is_test=False,
                   return_length=False, **kwargs):
    """Run ``decoder`` until all beams finish or ``max_step_num``.
    Reference: nn/decode.py::dynamic_decode."""
    inputs, states, finished = decoder.initialize(inits)
    outs = []
    step = 0
    max_steps = max_step_num if max_step_num is not None else 256
    while step < max_steps:
        out, states, inputs, finished = decoder.step(step, inputs, states,
                                                     **kwargs)
        outs.append(out)
        step += 1
        if bool(np.asarray(jax.device_get(jnp.all(finished)))):
            break

    stacked = type(outs[0])(*[jnp.stack([_raw(getattr(o, f))
                                         for o in outs])
                              for f in outs[0]._fields])
    final_out, final_states = decoder.finalize(
        stacked, states, getattr(states, "lengths", None))

    if not output_time_major:
        final_out = type(final_out)(*[
            Tensor(jnp.moveaxis(_raw(f), 0, 1)) if _raw(f).ndim >= 2 else f
            for f in final_out])
    if return_length:
        return final_out, final_states, Tensor(states.lengths)
    return final_out, final_states
