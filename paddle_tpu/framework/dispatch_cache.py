"""Signature-keyed compile cache for the eager dispatcher (the "eager
fast path").

The dygraph layer re-traces every differentiable op on every call:
``tensor.apply`` invokes ``jax.vjp`` per node, which costs ~0.5-1 ms of
host tracing per op even when the op itself is microseconds of compute.
This module makes steady-state eager execution trace-free:

* key  = (op identity, static args/kwargs, input avals — shape/dtype/
  weak-type, which positions are differentiated);
* value = a jitted forward returning ``(outputs, pullback)`` — the
  pullback is a ``jax.tree_util.Partial`` whose leaves are the vjp
  residuals, so partial-eval splits the vjp into two compiled halves —
  plus a jitted backward consuming ``(pullback, cotangents)``.  No-grad
  dispatches use a plain jitted forward.

Op identity for the per-call lambdas the op layer builds is the lambda's
``__code__`` object (shared across calls from the same source location)
plus its closure-cell values, which become part of the static key.

Safety:

* a signature only compiles once it has been seen ``_WARMUP`` times
  (``PADDLE_TPU_EAGER_CACHE_WARMUP``, default 32): a compile costs
  tens of ms while a hit saves well under one, so only loops hot
  enough to amortize it — real train loops, not a test's handful of
  iterations — ever pay one;
* any value that cannot be made a hashable static key (captured PRNG
  keys, Tensors, numpy arrays in closures, arbitrary objects) bypasses
  the cache — randomness is never baked into a compiled entry;
* ops whose python body is data-dependent (``.item()``, bool branches
  on values, dynamic output shapes) fail their first trace; the op is
  blacklisted and permanently falls back to the uncached path;
* bounded LRU (``PADDLE_TPU_EAGER_CACHE_SIZE``, default 1024 entries);
* ``PADDLE_TPU_EAGER_CACHE=0`` opts out entirely;
* :func:`invalidate` drops every entry (called on grad-hook and
  custom-vjp registration).

Counters (hits / misses / compiles / bypasses) are surfaced through
``paddle_tpu.framework.dispatch_stats()`` and ``paddle_tpu.profiler``.
"""
from __future__ import annotations

import enum
import functools
import os
import threading
import types
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["dispatch", "dispatch_stats", "reset_stats", "enabled",
           "set_enabled", "set_warmup", "invalidate"]

_BYPASS = object()

_enabled_flag = os.environ.get("PADDLE_TPU_EAGER_CACHE", "1").lower() \
    not in ("0", "false", "off")
_CAPACITY = max(8, int(os.environ.get("PADDLE_TPU_EAGER_CACHE_SIZE", "1024")))
_SEEN_CAPACITY = 4 * _CAPACITY
# sightings of a signature before it is worth compiling (see module
# docstring); the Nth sighting compiles, the first N-1 are misses
_WARMUP = max(1, int(os.environ.get("PADDLE_TPU_EAGER_CACHE_WARMUP", "32")))

_lock = threading.RLock()
_cache: "OrderedDict[tuple, _Entry]" = OrderedDict()
_seen: "OrderedDict[tuple, bool]" = OrderedDict()
# fn key -> reason string for ops whose trace failed (data-dependent
# python): the WHY is recorded so dispatch_stats()/tpu_lint can report
# which op fell off the fast path and what actually went wrong there
_blacklist: dict = {}
_epoch = 0           # bumped by invalidate(); part of every key

# megamorphic demotion: an op that keeps producing NEW signatures (a
# decode loop's per-step kv-cache shapes, padder churn) would compile
# once per shape forever; past this many distinct compiled signatures
# the op's new signatures bypass instead (existing entries keep hitting)
_POLY_LIMIT = max(1, int(os.environ.get("PADDLE_TPU_EAGER_CACHE_POLY",
                                        "16")))
_fn_sig_count: dict = {}


class _Stats:
    __slots__ = ("hits", "misses", "compiles", "bypasses", "invalidations")

    def __init__(self):
        self.reset()

    def reset(self):
        self.hits = 0
        self.misses = 0
        self.compiles = 0
        self.bypasses = 0
        self.invalidations = 0


_stats = _Stats()


def enabled() -> bool:
    return _enabled_flag


def set_warmup(n: int) -> int:
    """Runtime override of PADDLE_TPU_EAGER_CACHE_WARMUP (returns the
    previous value). Tests drop it to 2 so the cache engages inside a
    short loop; the default stays high because a compile only pays for
    itself after dozens of hits."""
    global _WARMUP
    prev = _WARMUP
    _WARMUP = max(1, int(n))
    return prev


def set_enabled(flag: bool) -> bool:
    """Runtime override of PADDLE_TPU_EAGER_CACHE (returns previous).
    Disabling drops all entries so re-enabling starts clean."""
    global _enabled_flag
    prev = _enabled_flag
    _enabled_flag = bool(flag)
    if not _enabled_flag:
        invalidate()
    return prev


def invalidate():
    """Drop every cached entry and seen-signature record. Called when op
    semantics may have shifted under the cache: grad-hook registration,
    custom-vjp (PyLayer) definition, or an explicit user reset."""
    global _epoch
    with _lock:
        _epoch += 1
        _cache.clear()
        _seen.clear()
        _blacklist.clear()
        _fn_sig_count.clear()
        _stats.invalidations += 1


def _fn_label(fnk):
    """Human-readable op label for a fn key (code objects carry their
    source location; everything else falls back to repr)."""
    if isinstance(fnk, tuple) and fnk and hasattr(fnk[0], "co_name"):
        code = fnk[0]
        fname = os.path.basename(code.co_filename)
        return f"{code.co_name} ({fname}:{code.co_firstlineno})"
    return repr(fnk)[:80]


def dispatch_stats() -> dict:
    """Snapshot of the eager-dispatch cache counters.

    ``compiles`` is the retrace count: a steady-state (warm) eager loop
    must add only ``hits``. ``blacklist`` lists every op that fell off
    the fast path with the recorded reason (exception type + message of
    its failed trace); ``megamorphic`` lists ops demoted for producing
    too many distinct signatures."""
    with _lock:
        return {"enabled": _enabled_flag, "hits": _stats.hits,
                "misses": _stats.misses, "compiles": _stats.compiles,
                "bypasses": _stats.bypasses,
                "invalidations": _stats.invalidations,
                "entries": len(_cache), "capacity": _CAPACITY,
                "blacklist": [{"op": _fn_label(k), "reason": r}
                              for k, r in list(_blacklist.items())[:32]],
                "megamorphic": [_fn_label(k)
                                for k, n in _fn_sig_count.items()
                                if n >= _POLY_LIMIT][:32],
                "aot": _aot_entry_sources()}


def _aot_entry_sources() -> dict:
    """Per-provenance entry counts (aot warm-start visibility): how many
    live cache entries were deserialized from disk vs compiled here."""
    out: dict = {}
    for e in _cache.values():
        for h in (e.fwd, e.bwd):
            if h is not None:
                out[h.source] = out.get(h.source, 0) + 1
    return out


def reset_stats():
    with _lock:
        _stats.reset()


# -- key construction --------------------------------------------------------

_SIMPLE = (type(None), bool, int, str, bytes, type(Ellipsis))


def _hkey(v):
    """Hashable static-key form of ``v``, or _BYPASS. Only value types
    whose semantics are fully captured by the key are allowed — arrays,
    Tensors and arbitrary objects (layers, PRNG keys) must bypass, or a
    compiled entry would bake a value that can change under it."""
    if isinstance(v, bool):  # before int: key True distinctly from 1
        return ("b", v)
    if isinstance(v, float):
        # hex() distinguishes -0.0 from 0.0 and collapses NaN payloads
        return ("f", v.hex())
    if isinstance(v, _SIMPLE):
        return v
    if isinstance(v, complex):
        return ("c", v.real.hex(), v.imag.hex())
    if isinstance(v, (tuple, list)):
        parts = tuple(_hkey(x) for x in v)
        if any(p is _BYPASS for p in parts):
            return _BYPASS
        return ("T" if isinstance(v, tuple) else "L",) + parts
    if isinstance(v, dict):
        try:
            items = sorted(v.items())
        except TypeError:
            return _BYPASS
        parts = tuple((k, _hkey(x)) for k, x in items)
        if any(p is _BYPASS for _, p in parts):
            return _BYPASS
        return ("D",) + parts
    if isinstance(v, slice):
        return ("S", _hkey(v.start), _hkey(v.stop), _hkey(v.step))
    if isinstance(v, np.dtype):
        return ("dt", v.str)
    if isinstance(v, enum.Enum):
        return ("E", type(v).__name__, v.name)
    if isinstance(v, (np.integer, np.floating, np.bool_)) and v.ndim == 0:
        return ("np", v.dtype.str, v.item())
    if isinstance(v, type):  # dtype classes (jnp.float32), Tensor classes
        return v
    if callable(v):
        return _fn_key(v)
    return _BYPASS


def _fn_key(fn):
    """Stable identity for the dispatched op. Per-call lambdas share
    their ``__code__``; their captured values join the key."""
    if isinstance(fn, functools.partial):
        sub = _fn_key(fn.func)
        args = _hkey(tuple(fn.args))
        kw = _hkey(fn.keywords or {})
        if _BYPASS in (sub, args, kw):
            return _BYPASS
        return ("P", sub, args, kw)
    if isinstance(fn, types.MethodType):
        return _BYPASS  # bound methods drag in mutable instance state
    code = getattr(fn, "__code__", None)
    if code is None:
        # builtin / jnp ufunc: a stable module-level object — but C
        # callables (ctypes funcptrs) may be unhashable
        try:
            hash(fn)
        except TypeError:
            return _BYPASS
        return fn
    cells = ()
    if fn.__closure__:
        vals = []
        for cell in fn.__closure__:
            try:
                hv = _hkey(cell.cell_contents)
            except ValueError:  # empty cell
                return _BYPASS
            if hv is _BYPASS:
                return _BYPASS
            vals.append(hv)
        cells = tuple(vals)
    defaults = _hkey(fn.__defaults__ or ())
    if defaults is _BYPASS:
        return _BYPASS
    return (code, cells, defaults)


def _classify(raw):
    """Split positional args into dynamic arrays vs static values.

    Returns (template, dyn_vals, avals) or None to bypass. template is a
    tuple of 'd' / ('s', hkey); numpy arrays ride as dynamic args."""
    template = []
    dyn_vals = []
    avals = []
    for v in raw:
        if isinstance(v, jax.core.Tracer):
            return None  # inside jit/vmap/grad tracing: not our business
        if isinstance(v, jax.Array):
            template.append("d")
            dyn_vals.append(v)
            avals.append(v.aval)  # ShapedArray: shape+dtype+weak_type
        elif isinstance(v, np.ndarray):
            template.append("d")
            dyn_vals.append(v)
            avals.append(("np", v.shape, v.dtype.str))
        else:
            hk = _hkey(v)
            if hk is _BYPASS:
                return None
            template.append(("s", hk))
    return tuple(template), dyn_vals, tuple(avals)


# -- compiled entries --------------------------------------------------------

def _as_struct(x):
    return jax.ShapeDtypeStruct(x.shape, x.dtype) \
        if hasattr(x, "shape") and hasattr(x, "dtype") else x


def _any_tracer(*trees):
    for t in trees:
        for leaf in jax.tree_util.tree_leaves(t):
            if isinstance(leaf, jax.core.Tracer):
                return True
    return False


class _Entry:
    """One compiled signature: AOT fwd/bwd program handles.

    Residuals cross the fwd jit boundary as a FLAT tuple of arrays (not
    the ``jax.tree_util.Partial`` pullback jax.vjp returns): Partial
    pytree defs embed vjp closure functions that cannot be pickled, and
    flat tuples are what lets the AOT service serialize both halves to
    disk. The pullback tree structure is captured host-side during the
    fwd trace (``_res_cell``); a warm process that restored fwd from
    disk never needs it unless bwd misses, in which case the fwd is
    re-traced abstractly (lower only, no compile) to recover it.
    """

    __slots__ = ("fwd", "bwd", "label", "_fwd_warm", "_bwd_warm",
                 "_sig_mat", "_fwd_jitted", "_bwd_jitted", "_fwd_struct",
                 "_res_cell")

    def __init__(self, label="", sig_mat=None):
        self.fwd = None
        self.bwd = None
        # compile attribution: the first execution of each half traces
        # and compiles — scope it under the op label so the XLA compile
        # lands in paddle_xla_compiles_total{origin="eager:<op>"}; warm
        # calls pay one attribute check
        self.label = label
        self._fwd_warm = False
        self._bwd_warm = False
        self._sig_mat = sig_mat
        self._fwd_jitted = None
        self._bwd_jitted = None
        self._fwd_struct = None
        self._res_cell = {}

    def forward(self, dyn_vals):
        if self._fwd_warm:
            return self.fwd.call(tuple(dyn_vals), runtime_zero())
        from ..observability.compile_attr import compile_scope
        with compile_scope(f"eager:{self.label}"):
            out = self.fwd.call(tuple(dyn_vals), runtime_zero())
        self._fwd_warm = True
        return out

    def _ensure_res_tree(self):
        if "tree" not in self._res_cell:
            # abstract re-trace of fwd (lower only — no backend compile)
            # reruns the host-side flatten and fills the cell
            self._fwd_jitted.lower(*self._fwd_struct)

    def _make_bwd_jitted(self):
        if self._bwd_jitted is None:
            self._ensure_res_tree()
            tree = self._res_cell["tree"]

            def bwd(flat, cts, zero):
                pb = jax.tree_util.tree_unflatten(tree, list(flat))
                return bitwise_call(zero, lambda c: pb(c), cts)

            self._bwd_jitted = jax.jit(bwd)
        return self._bwd_jitted

    def backward(self, flat_res, cts):
        zero = runtime_zero()
        if _any_tracer(flat_res, cts):
            # grad-of-grad traces through the cached bwd: only the live
            # jitted program composes with an outer trace
            return self._make_bwd_jitted()(flat_res, cts, zero)
        if self.bwd is None:
            from ..aot import get_service
            self.bwd = get_service().get(
                "eager-bwd", args=(flat_res, cts, zero),
                key_parts=("eager-bwd", self._sig_mat),
                jitted_thunk=self._make_bwd_jitted,
                origin=f"eager:{self.label}")
        if self._bwd_warm:
            return self.bwd.call(flat_res, cts, zero)
        from ..observability.compile_attr import compile_scope
        with compile_scope(f"eager:{self.label}"):
            out = self.bwd.call(flat_res, cts, zero)
        self._bwd_warm = True
        return out


def _build_entry(fn, kwargs, template, statics, diff_idx, label="",
                 sig_mat=None, dyn_vals=()):
    """Build the AOT fwd (and lazily bwd) programs for one signature.

    ``statics`` are the live static arg values in template order (the key
    pinned them, so baking them into the trace is sound). Both halves run
    through :func:`bitwise_call`, so the compiled programs reproduce the
    uncached path's per-op rounding exactly. With the AOT disk cache
    enabled the fwd program is resolved through the service (a warm
    process deserializes the executable — zero trace, zero compile);
    without it the live jitted callable compiles on first execution,
    exactly the pre-AOT behavior."""
    from ..aot import get_service

    n = len(template)
    dyn_pos = tuple(i for i, t in enumerate(template) if t == "d")
    static_by_pos = {}
    it = iter(statics)
    for i, t in enumerate(template):
        if t != "d":
            static_by_pos[i] = next(it)

    def assemble(dyn):
        vals = [None] * n
        for i, v in zip(dyn_pos, dyn):
            vals[i] = v
        for i, v in static_by_pos.items():
            vals[i] = v
        return vals

    entry = _Entry(label=label, sig_mat=sig_mat)

    if not diff_idx:
        def fwd(dyn, zero):
            def run(dyn):
                return fn(*assemble(dyn), **kwargs)
            return bitwise_call(zero, run, dyn)
    else:
        cell = entry._res_cell

        def fwd(dyn, zero):
            def run(dyn):
                vals = assemble(dyn)

                def closed(*diff_vals):
                    v2 = list(vals)
                    for i, dv in zip(diff_idx, diff_vals):
                        v2[i] = dv
                    return fn(*v2, **kwargs)

                # jax.vjp under jit partial-evals the op: primal outputs
                # plus a Partial pullback whose leaves are the residuals
                return jax.vjp(closed, *(vals[i] for i in diff_idx))

            out, pullback = bitwise_call(zero, run, dyn)
            flat, tree = jax.tree_util.tree_flatten(pullback)
            cell["tree"] = tree
            return out, tuple(flat)

    entry._fwd_jitted = jax.jit(fwd)
    args = (tuple(dyn_vals), runtime_zero())
    entry._fwd_struct = jax.tree_util.tree_map(_as_struct, args)
    entry.fwd = get_service().get(
        "eager-fwd", args=args, key_parts=("eager-fwd", sig_mat),
        jitted=entry._fwd_jitted, origin=f"eager:{label}")
    return entry


# -- the dispatcher ----------------------------------------------------------

def dispatch(fn, raw, kwargs, diff_idx):
    """Fast-path attempt for one eager op.

    Returns None when the caller must run the uncached path (bypass or
    cold signature), else ``(out, pullback, entry)`` — ``pullback`` is
    None for no-grad dispatches.
    """
    try:
        cls = _classify(raw)
        if cls is None:
            _stats.bypasses += 1
            return None
        template, dyn_vals, avals = cls
        fnk = _fn_key(fn)
        if fnk is _BYPASS or fnk in _blacklist:
            _stats.bypasses += 1
            return None
        kwk = _hkey(kwargs) if kwargs else ()
        if kwk is _BYPASS:
            _stats.bypasses += 1
            return None
        key = (_epoch, fnk, template, avals, kwk, diff_idx)
        hash(key)
    except TypeError:  # unhashable corner smuggled through _hkey
        _stats.bypasses += 1
        return None

    with _lock:
        entry = _cache.get(key)
        if entry is not None:
            _cache.move_to_end(key)
            _stats.hits += 1
        elif _fn_sig_count.get(fnk, 0) >= _POLY_LIMIT:
            _stats.bypasses += 1  # megamorphic op: stop compiling shapes
            return None
        else:
            cnt = _seen.get(key, 0) + 1
            if cnt < _WARMUP:
                # still warming: record the sighting and fall back — a
                # compile costs tens of ms and a hit saves <1 ms, so
                # cold/one-shot signatures must never pay one
                _seen[key] = cnt
                _seen.move_to_end(key)
                while len(_seen) > _SEEN_CAPACITY:
                    _seen.popitem(last=False)
                _stats.misses += 1
                return None

    if entry is None:
        statics = [v for v, t in zip(raw, template) if t != "d"]
        try:
            # sig material for the AOT disk key: the in-memory key minus
            # the process-local epoch (code objects/values render stably
            # through aot.keys; epoch invalidation is conservative — the
            # rebuilt program is identical, so a disk restore is correct)
            entry = _build_entry(fn, dict(kwargs), template, statics,
                                 diff_idx, label=_fn_label(fnk),
                                 sig_mat=(fnk, template, avals, kwk,
                                          diff_idx),
                                 dyn_vals=dyn_vals)
        except Exception as e:
            with _lock:
                _blacklist[fnk] = \
                    f"build failed: {type(e).__name__}: {str(e)[:200]}"
                _stats.bypasses += 1
            return None
        with _lock:
            _stats.compiles += 1
            if len(_fn_sig_count) > _SEEN_CAPACITY:
                _fn_sig_count.clear()  # bound bookkeeping, keep entries
            _fn_sig_count[fnk] = _fn_sig_count.get(fnk, 0) + 1
            _cache[key] = entry
            _seen.pop(key, None)
            while len(_cache) > _CAPACITY:
                _cache.popitem(last=False)

    try:
        if diff_idx:
            out, pullback = entry.forward(dyn_vals)
        else:
            out, pullback = entry.forward(dyn_vals), None
    except Exception as e:
        # the first execution traces; data-dependent python (.item(),
        # value branches, dynamic output shapes) surfaces here — fall
        # back for good, the eager path reports the real error if any
        with _lock:
            _cache.pop(key, None)
            _blacklist[fnk] = \
                f"first trace failed: {type(e).__name__}: {str(e)[:200]}"
            _stats.bypasses += 1
        return None
    return out, pullback, entry


# -- bitwise-faithful fused evaluation ---------------------------------------

_INT_FOR_WIDTH = {2: jnp.int16, 4: jnp.int32}

# primitives whose raw params can't round-trip through Primitive.bind;
# their primal body is inlined instead (matching eager, which executes
# the undifferentiated body op-by-op)
_INLINE_CALLS = ("custom_jvp_call", "custom_vjp_call",
                 "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
                 "custom_lin")


def _seal(x, zero):
    """Bitwise identity (xor with a runtime-zero mask) that neither XLA
    nor LLVM can see through, so a consumer add can never FMA-contract
    with the producer of ``x``."""
    from jax import lax

    dt = jnp.dtype(x.dtype)
    if not jnp.issubdtype(dt, jnp.floating):
        return x
    it = _INT_FOR_WIDTH.get(dt.itemsize)
    if it is None:
        return x
    mask = lax.convert_element_type(zero, it)
    return lax.bitcast_convert_type(
        lax.bitcast_convert_type(x, it) ^ mask, dt)


def _eval_sealed(jaxpr, consts, args, zero):
    from jax.util import safe_map

    env = {}

    def read(var):
        return var.val if isinstance(var, jax.core.Literal) else env[var]

    def write(var, val):
        env[var] = val

    safe_map(write, jaxpr.constvars, consts)
    safe_map(write, jaxpr.invars, args)
    for eqn in jaxpr.eqns:
        invals = safe_map(read, eqn.invars)
        if eqn.primitive.name in _INLINE_CALLS:
            inner = eqn.params.get("call_jaxpr") or eqn.params.get("fun_jaxpr")
            outs = _eval_sealed(inner.jaxpr, inner.consts, invals, zero)
        else:
            outs = eqn.primitive.bind(*invals, **eqn.params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
        outs = [_seal(o, zero) for o in outs]
        safe_map(write, eqn.outvars, outs)
    return safe_map(read, jaxpr.outvars)


def bitwise_call(zero, fn, *args):
    """Run ``fn`` under the current trace with every float primitive
    output sealed against cross-op fusion.

    A jitted composite lets XLA's CPU backend contract mul+add chains
    into FMAs, which rounds once where the eager op-by-op path rounds
    twice — a fused program would drift from the uncached path by an ulp
    per axpy. Interpreting the jaxpr and xor-sealing each float output
    with ``zero`` (a runtime-zero i32 scalar the compiler cannot fold)
    keeps every primitive's result exactly the eagerly-computed bits
    while still compiling to ONE dispatch. Higher-order custom-grad
    calls are inlined; pjit/control-flow eqns re-bind as units, which is
    what eager execution compiles them as too."""
    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
    out_tree = jax.tree_util.tree_structure(out_shape)
    flat_args = jax.tree_util.tree_leaves(args)
    out_flat = _eval_sealed(closed.jaxpr, closed.consts, flat_args, zero)
    return jax.tree_util.tree_unflatten(out_tree, out_flat)


_zero_cache = None


def runtime_zero():
    """The i32 zero passed to sealed programs as a runtime argument (a
    constant would be folded and the seals optimized away). device_put
    of a host zero, NOT jnp.zeros — the latter is itself a tiny XLA
    program and would be the one unavoidable backend compile in an
    otherwise fully warm AOT-cached process."""
    global _zero_cache
    if _zero_cache is None:
        _zero_cache = jax.device_put(np.zeros((), np.int32))
    return _zero_cache


# -- jitted tree helpers (cotangent accumulation, seeds) ---------------------

@jax.jit
def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


@jax.jit
def _ones_like(a):
    return jnp.ones_like(a)


# (name, avals) -> AotProgram for the tiny per-signature helper programs
# above: with the AOT disk cache enabled even these restore in a warm
# process instead of compiling (they are part of every backward pass, so
# they count against the fresh-subprocess zero-compile budget)
_helper_handles: dict = {}


def _aot_helper(name, jitted, args):
    from ..aot import get_service
    svc = get_service()
    if not svc.persistent:
        return None
    try:
        key = (name,) + tuple(
            (tuple(a.shape), str(a.dtype),
             bool(getattr(a, "weak_type", False))) for a in args)
        h = _helper_handles.get(key)
        if h is None:
            h = svc.get(f"eager-{name}", args=args,
                        key_parts=("helper", name), jitted=jitted,
                        origin=f"eager:{name}")
            if len(_helper_handles) > 512:
                _helper_handles.clear()
            _helper_handles[key] = h
        return h
    except Exception:
        return None


def ct_add(a, b):
    """Cotangent accumulation: jitted when the cache is on (saves one
    eager dispatch per accumulation in backward())."""
    if not _enabled_flag:
        return a + b
    if isinstance(a, jax.core.Tracer) or isinstance(b, jax.core.Tracer):
        return a + b
    if getattr(a, "dtype", None) != getattr(b, "dtype", None) or \
            getattr(a, "shape", None) != getattr(b, "shape", None):
        return a + b  # mixed avals: let eager promotion rules decide
    h = _aot_helper("ct_add", _tree_add, (a, b))
    if h is not None:
        return h.call(a, b)
    return _tree_add(a, b)


def ones_like_ct(a):
    if not _enabled_flag or isinstance(a, jax.core.Tracer):
        return jnp.ones_like(a)
    h = _aot_helper("ones_like", _ones_like, (a,))
    if h is not None:
        return h.call(a)
    return _ones_like(a)
