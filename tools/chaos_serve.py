#!/usr/bin/env python
"""chaos_serve — drive the serving EngineSupervisor (or a whole
ReplicaFleet) through an injected fault and emit a JSON verdict ledger
(the check_* tool contract; chaos_train.py's serving counterpart).

A tiny llama serves a staggered, SAMPLED workload (per-request seeds, so
the verdict also proves the PRNG-chain resume) twice: once uninterrupted
on a plain Engine (the baseline), once under
``serving.resilience.EngineSupervisor`` with a ChaosMonkey firing the
chosen serving fault at the chosen supervised step. The verdict asserts
every surviving request's full output is token-identical to the
uninterrupted run.

    JAX_PLATFORMS=cpu python tools/chaos_serve.py --fault stall --json
    JAX_PLATFORMS=cpu python tools/chaos_serve.py --fault corrupt --step 5
    JAX_PLATFORMS=cpu python tools/chaos_serve.py --fault abandon

Faults: stall (wedged decode) | raise (decode error) | corrupt (KV slot
poisoned; probe must detect before decode consumes it) | abandon (client
disconnect mid-stream) | none. Exit code 0 iff the run recovered with
token-identical survivors.

``--spec`` runs the verdict against a SPECULATIVE engine
(``Engine(speculative=SpecConfig(draft="ngram", k=4))``) wrapped in the
supervisor, with part of the workload vocab-masked repetitive so the
verify program provably runs before the fault fires. The baseline is
the plain NON-speculative engine — the verdict asserts the recovered
speculative run is token-identical to it (the speculative token-
identity contract composed with fault recovery) and that the
acceptance counters survive the rebuild (``EngineSupervisor``
accumulates condemned incarnations' spec counters):

    JAX_PLATFORMS=cpu python tools/chaos_serve.py --spec --fault raise

``--fleet N`` runs the fleet verdict instead: N supervised replicas
behind a ``ReplicaFleet`` serve the shared-prefix workload GREEDY and
SAMPLED while the fault (kill = replica-kill | stall | raise | corrupt |
flap = route-flap | none) fires mid-decode into one replica. The verdict
asserts ``zero_lost`` (every request finishes) and ``token_identical``
(every output equals the uninterrupted SINGLE-ENGINE baseline — the
in-flight requests of the faulted replica complete via cross-replica
``adopt()`` migration) in BOTH arms:

    JAX_PLATFORMS=cpu python tools/chaos_serve.py --fleet 3 --fault kill
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_FAULT_MAP = {"stall": "decode-stall", "raise": "decode-raise",
              "corrupt": "kv-corrupt", "abandon": "abandon"}
_FLEET_FAULT_MAP = {"kill": "replica-kill", "stall": "decode-stall",
                    "raise": "decode-raise", "corrupt": "kv-corrupt",
                    "flap": "route-flap"}


def _workload(seed):
    """Deterministic staggered workload: (prompt, max_new, temp, seed)
    per request, plus the submission schedule (request idx -> steps to
    pump before the next arrival). ≥3 requests in flight at different
    positions when a mid-run fault fires. The first two requests share
    an 8-token system prefix — one full paged block — so the kv-corrupt
    fault has a live SHARED prefix block to poison (the nastiest case:
    every sharer reads it, and replay must heal them all)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    sys_prefix = rng.integers(0, 1000, (8,)).astype(np.int32)

    def prompt(n, shared):
        tail = rng.integers(0, 1000, (int(n),)).astype(np.int32)
        return np.concatenate([sys_prefix, tail]) if shared else tail

    reqs = [(prompt(n, sh), int(m), float(t), int(s))
            for n, m, t, s, sh in ((3, 8, 0.8, 11, True),
                                   (4, 8, 1.2, 7, True),
                                   (5, 7, 0.6, 3, False),
                                   (6, 6, 1.0, 23, False))]
    schedule = (2, 1, 1, 0)     # decode steps pumped after each submit
    return reqs, schedule


def _run(server, reqs, schedule):
    """Submit the workload on the given engine/supervisor, pump to
    completion, return the handles (order = submission order)."""
    handles = []
    for (ids, m, t, s), pump in zip(reqs, schedule):
        handles.append(server.submit(ids, max_new_tokens=m, temperature=t,
                                     seed=s))
        for _ in range(pump):
            server.step()
    while any(not h.finished for h in handles):
        server.step()
    return handles


def _verdict(fault, step, seed, stall_s):
    import dataclasses

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.resilience import ChaosMonkey
    from paddle_tpu.serving import Engine, EngineSupervisor
    from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

    # spans for the chaotic run (the fault's trace id in the verdict
    # points into this ring — dump with tools/obs_dump.py --trace)
    obs.enable_tracing()

    cfg = dataclasses.replace(LLAMA_TINY, dtype="float32",
                              num_hidden_layers=2)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    kw = dict(n_slots=2, max_len=64, min_prompt_bucket=4, do_sample=True,
              top_k=8, block_size=8)     # 8-token blocks: the shared
    reqs, schedule = _workload(seed)     # prefix aliases one full block

    baseline = _run(Engine(model, **kw), reqs, schedule)
    base_tokens = [list(h.tokens) for h in baseline]

    chaos = ChaosMonkey(seed=seed,
                        at=({int(step): _FAULT_MAP[fault]}
                            if fault != "none" else {}),
                        stall_s=stall_s)
    sup = EngineSupervisor(model, chaos=chaos, step_timeout_s=None,
                           kv_probe_interval=1, **kw)
    handles = _run(sup, reqs, schedule)

    abandoned = [h for h in handles if h.finish_reason == "cancelled"]
    survivors = [(i, h) for i, h in enumerate(handles)
                 if h.finish_reason not in ("cancelled",)]
    mismatches = [i for i, h in survivors if list(h.tokens) != base_tokens[i]]
    fired = list(chaos.fired)
    expected_counter = {"stall": sup.wedges + sup.step_errors,
                        "raise": sup.step_errors,
                        "corrupt": sup.kv_corruptions,
                        "abandon": sup.abandoned}.get(fault, 0)
    detected = fault == "none" or (bool(fired) and expected_counter > 0)
    recovered = (fault in ("none", "abandon")
                 or sup.rebuilds > 0) and not mismatches
    # the engine must still be healthy after the fault: everything done
    idle = (sup.engine.cache.n_active == 0
            and sup.engine.scheduler.queue_depth == 0)
    # paged-pool hygiene: block/radix refcounts must balance after the
    # fault + replay, and the corrupt fault must have exercised prefix
    # sharing (the poisoned block had sharers to heal)
    refcounts_ok = sup.engine.cache.check_refcounts()
    shared_tokens = sup.engine.metrics.prefix_hit_tokens
    shared_ok = fault != "corrupt" or shared_tokens > 0
    ok = bool(detected and recovered and idle and refcounts_ok
              and shared_ok
              and (fault != "abandon" or len(abandoned) == 1))
    return {
        "fault": fault, "injected_step": step, "seed": seed,
        "requests": len(reqs), "fired": fired,
        "trace_id": chaos.last_trace_id,
        "request_trace_ids": [h.trace_id for h in handles],
        "rebuilds": sup.rebuilds, "replayed": sup.replayed,
        "wedges": sup.wedges, "step_errors": sup.step_errors,
        "kv_corruptions": sup.kv_corruptions, "abandoned": sup.abandoned,
        "survivors": len(survivors), "mismatched_requests": mismatches,
        "token_identical": not mismatches,
        "refcounts_consistent": refcounts_ok,
        "prefix_hit_tokens": int(shared_tokens),
        "ledger": sup.ledger.counts(),
        "ok": ok,
    }


def _spec_workload(seed, vocab):
    """Speculative chaos workload: two vocab-masked repetitive requests
    (the emitted stream repeats, so the n-gram proposer fires and the
    verify program runs before the fault) plus two plain sampled ones
    (the fused-decode fallback path). Returns (prompt, kwargs) pairs +
    the pump schedule."""
    import numpy as np

    rng = np.random.default_rng(seed)
    a, b = (int(t) for t in rng.integers(10, vocab - 10, (2,)))
    va = np.zeros(vocab, bool)
    va[a] = True
    vb = np.zeros(vocab, bool)
    vb[[a, b]] = True
    reqs = [
        (np.full((9,), a, np.int32),
         dict(max_new_tokens=10, temperature=0.8, seed=11,
              logit_mask=va)),
        (np.asarray([a, b] * 5, np.int32),
         dict(max_new_tokens=9, temperature=1.2, seed=7,
              logit_mask=vb)),
        (rng.integers(0, 1000, (5,)).astype(np.int32),
         dict(max_new_tokens=7, temperature=0.6, seed=3)),
        (rng.integers(0, 1000, (6,)).astype(np.int32),
         dict(max_new_tokens=6, temperature=1.0, seed=23)),
    ]
    schedule = (2, 1, 1, 0)
    return reqs, schedule


def _run_kw(server, reqs, schedule):
    handles = []
    for (ids, kw), pump in zip(reqs, schedule):
        handles.append(server.submit(ids, **kw))
        for _ in range(pump):
            server.step()
    while any(not h.finished for h in handles):
        server.step()
    return handles


def _spec_verdict(fault, step, seed, stall_s):
    """Speculative engine under chaos: recovered output must equal the
    NON-speculative uninterrupted baseline (token-identity composed
    through rebuild-and-replay), verify must have actually run, pool
    refcounts must balance, and the acceptance counters must survive
    the rebuild."""
    import dataclasses

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.resilience import ChaosMonkey
    from paddle_tpu.serving import Engine, EngineSupervisor, SpecConfig
    from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

    obs.enable_tracing()
    cfg = dataclasses.replace(LLAMA_TINY, dtype="float32",
                              num_hidden_layers=2)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    kw = dict(n_slots=2, max_len=64, min_prompt_bucket=4, do_sample=True,
              top_k=8, block_size=8)
    reqs, schedule = _spec_workload(seed, cfg.vocab_size)

    baseline = _run_kw(Engine(model, **kw), reqs, schedule)
    base_tokens = [list(h.tokens) for h in baseline]

    chaos = ChaosMonkey(seed=seed,
                        at=({int(step): _FAULT_MAP[fault]}
                            if fault != "none" else {}),
                        stall_s=stall_s)
    sup = EngineSupervisor(
        model, chaos=chaos, step_timeout_s=None, kv_probe_interval=1,
        speculative=SpecConfig(draft="ngram", k=4), **kw)
    handles = _run_kw(sup, reqs, schedule)

    survivors = [(i, h) for i, h in enumerate(handles)
                 if h.finish_reason not in ("cancelled",)]
    mismatches = [i for i, h in survivors
                  if list(h.tokens) != base_tokens[i]]
    fired = list(chaos.fired)
    expected_counter = {"stall": sup.wedges + sup.step_errors,
                        "raise": sup.step_errors,
                        "corrupt": sup.kv_corruptions,
                        "abandon": sup.abandoned}.get(fault, 0)
    detected = fault == "none" or (bool(fired) and expected_counter > 0)
    recovered = (fault in ("none", "abandon")
                 or sup.rebuilds > 0) and not mismatches
    idle = (sup.engine.cache.n_active == 0
            and sup.engine.scheduler.queue_depth == 0)
    refcounts_ok = sup.engine.cache.check_refcounts()
    spec_total = sup.spec_counters()
    # the rebuild must not zero acceptance history: when an incarnation
    # was condemned, its pre-fault counters live in sup.spec_totals
    counters_survived = (sup.rebuilds == 0
                         or sup.spec_totals["spec_steps"] > 0)
    ok = bool(detected and recovered and idle and refcounts_ok
              and spec_total["spec_steps"] > 0
              and spec_total["spec_accepted_tokens"] > 0
              and counters_survived)
    return {
        "fault": fault, "injected_step": step, "seed": seed,
        "speculative": {"draft": "ngram", "k": 4},
        "requests": len(reqs), "fired": fired,
        "trace_id": chaos.last_trace_id,
        "rebuilds": sup.rebuilds, "replayed": sup.replayed,
        "wedges": sup.wedges, "step_errors": sup.step_errors,
        "kv_corruptions": sup.kv_corruptions,
        "survivors": len(survivors), "mismatched_requests": mismatches,
        "token_identical": not mismatches,
        "refcounts_consistent": refcounts_ok,
        "spec_counters_total": spec_total,
        "spec_counters_survived_rebuild": counters_survived,
        "acceptance_rate": (
            None if not spec_total["spec_proposed_tokens"]
            else round(spec_total["spec_accepted_tokens"]
                       / spec_total["spec_proposed_tokens"], 4)),
        "ledger": sup.ledger.counts(),
        "ok": ok,
    }


def _fleet_verdict(fault, step, seed, stall_s, n_replicas):
    """The fleet robustness headline, both sampling modes: kill / wedge
    / corrupt one of N replicas mid-decode (or flap the router) — zero
    requests lost, every output token-identical to an uninterrupted
    single-engine baseline, replicas re-registered, pools consistent."""
    import dataclasses

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.resilience import ChaosMonkey
    from paddle_tpu.serving import Engine, ReplicaFleet

    from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

    obs.enable_tracing()
    cfg = dataclasses.replace(LLAMA_TINY, dtype="float32",
                              num_hidden_layers=2)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    reqs, schedule = _workload(seed)
    chaos_fault = _FLEET_FAULT_MAP.get(fault)

    arms = {}
    for arm, sample_kw in (("greedy", {}),
                           ("sampled", dict(do_sample=True, top_k=8))):
        kw = dict(n_slots=2, max_len=64, min_prompt_bucket=4,
                  block_size=8, **sample_kw)
        baseline = _run(Engine(model, **kw), reqs, schedule)
        base_tokens = [list(h.tokens) for h in baseline]

        chaos = ChaosMonkey(seed=seed,
                            at=({int(step): chaos_fault}
                                if chaos_fault else {}),
                            stall_s=stall_s)
        fleet = ReplicaFleet(model, n_replicas, chaos=chaos,
                             kv_probe_interval=1, **kw)
        handles = _run(fleet, reqs, schedule)
        trace_pre = [h.trace_id for h in handles]

        lost = [i for i, h in enumerate(handles)
                if h.finish_reason != "length"]
        mismatches = [i for i, h in enumerate(handles)
                      if list(h.tokens) != base_tokens[i]]
        refcounts_ok = all(
            r.engine.cache.check_refcounts()
            for r in fleet.replicas.values())
        states = fleet.replica_states()
        c = fleet.counters()
        # fault-specific evidence that the injection actually happened
        # and was recovered from (not silently skipped)
        evidence = {
            "kill": c["replica_kills"] > 0 and c["migrations"] > 0,
            "stall": c["migrations"] > 0,
            "raise": c["migrations"] > 0,
            "corrupt": sum(r.sup.kv_corruptions
                           for r in fleet.replicas.values()) > 0,
            "flap": c["route_flaps"] > 0,
            "none": True,
        }[fault]
        arm_ok = (not lost and not mismatches and refcounts_ok
                  and evidence and fleet.n_pending == 0
                  and c["condemned"] == 0
                  and all(s == "healthy" for s in states.values())
                  and [h.trace_id for h in handles] == trace_pre)
        arms[arm] = {
            "fired": list(chaos.fired), "lost": lost,
            "mismatched_requests": mismatches,
            "token_identical": not mismatches,
            "zero_lost": not lost,
            "migrations": c["migrations"],
            "replica_kills": c["replica_kills"],
            "route_flaps": c["route_flaps"],
            "prefix_routed": c["prefix_routed"],
            "re_registers": c["re_registers"],
            "states": states,
            "refcounts_consistent": refcounts_ok,
            "request_trace_ids": trace_pre,
            "ledger": fleet.ledger.counts(),
            "ok": bool(arm_ok),
        }
    ok = all(a["ok"] for a in arms.values())
    return {
        "fleet": n_replicas, "fault": fault, "injected_step": step,
        "seed": seed, "requests": len(reqs),
        "token_identical": all(a["token_identical"]
                               for a in arms.values()),
        "zero_lost": all(a["zero_lost"] for a in arms.values()),
        "arms": arms, "ok": bool(ok),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="chaos_serve",
        description="deterministic serving chaos vs the engine "
        "supervisor / replica fleet (JSON verdict ledger)")
    ap.add_argument("--fault", default="stall",
                    choices=("stall", "raise", "corrupt", "abandon",
                             "kill", "flap", "none"))
    ap.add_argument("--step", type=int, default=4,
                    help="0-based supervised step at which the fault "
                    "fires (mid-decode for the default workload)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--stall-s", type=float, default=0.05)
    ap.add_argument("--spec", action="store_true",
                    help="speculative mode: ngram-draft engine under "
                    "the supervisor; verdict = token_identical vs the "
                    "NON-speculative baseline + acceptance counters "
                    "survive the rebuild")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet mode: N supervised replicas behind a "
                    "ReplicaFleet; faults kill/stall/raise/corrupt/"
                    "flap; verdict = zero_lost + token_identical vs a "
                    "single-engine baseline, greedy AND sampled")
    ap.add_argument("--json", action="store_true", help="emit a JSON line")
    args = ap.parse_args(argv)

    if args.fleet:
        if args.fault == "abandon":
            ap.error("--fleet has no abandon fault (use the "
                     "single-engine mode)")
        record = {"bench": "chaos_serve_fleet",
                  **_fleet_verdict(args.fault, args.step, args.seed,
                                   args.stall_s, args.fleet)}
        if args.json:
            print(json.dumps(record, default=str))
        else:
            for k in ("fault", "injected_step", "requests",
                      "token_identical", "zero_lost"):
                print(f"{k:18s} {record[k]}")
            for arm, a in record["arms"].items():
                print(f"{arm:>8s}: migrations={a['migrations']} "
                      f"kills={a['replica_kills']} states={a['states']}")
            print("OK (fleet recovered, token-identical, zero lost)"
                  if record["ok"] else
                  "FAIL: fleet lost requests or diverged")
        return 0 if record["ok"] else 1

    if args.fault in ("kill", "flap"):
        ap.error(f"--fault {args.fault} requires --fleet N")
    if args.spec:
        record = {"bench": "chaos_serve_spec",
                  **_spec_verdict(args.fault, args.step, args.seed,
                                  args.stall_s)}
        if args.json:
            print(json.dumps(record, default=str))
        else:
            for k in ("fault", "injected_step", "requests", "rebuilds",
                      "replayed", "survivors", "token_identical",
                      "acceptance_rate",
                      "spec_counters_survived_rebuild"):
                print(f"{k:30s} {record[k]}")
            print("OK (speculative run recovered token-identically)"
                  if record["ok"] else
                  "FAIL: speculative run diverged or lost acceptance "
                  "counters")
        return 0 if record["ok"] else 1
    record = {"bench": "chaos_serve",
              **_verdict(args.fault, args.step, args.seed, args.stall_s)}
    if args.json:
        print(json.dumps(record, default=str))
    else:
        for k in ("fault", "injected_step", "requests", "rebuilds",
                  "replayed", "survivors", "token_identical"):
            print(f"{k:18s} {record[k]}")
        print("OK (recovered, token-identical)" if record["ok"]
              else "FAIL: did not recover token-identically")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
