"""global_scatter / global_gather (reference distributed/utils.py:57,180):
the MoE token-dispatch collectives, reproduced bit-for-bit from the
reference docstring's 2-card example under shard_map on the virtual CPU
mesh, plus the eager single-controller path and dtype validation."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.utils import (
    _global_gather_raw, _global_scatter_raw, global_gather, global_scatter)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >=2 devices")


# the reference docstring example: world_size 2, n_expert 2, d_model 2
X_ROWS = np.array([[1, 2], [3, 4], [5, 6], [7, 8], [9, 10]], np.float32)
LC = np.array([[2, 1, 1, 1], [1, 1, 2, 1]], np.int64)  # per-rank counts
GC = np.array([[2, 1, 1, 1], [1, 1, 2, 1]], np.int64)
SCATTER_EXPECTED = [
    np.array([[1, 2], [3, 4], [1, 2], [5, 6], [3, 4]], np.float32),
    np.array([[7, 8], [5, 6], [7, 8], [9, 10], [9, 10]], np.float32),
]
GATHER_EXPECTED = [
    np.array([[1, 2], [3, 4], [7, 8], [1, 2], [7, 8]], np.float32),
    np.array([[5, 6], [9, 10], [3, 4], [5, 6], [9, 10]], np.float32),
]


def _mesh2():
    return Mesh(np.asarray(jax.devices()[:2]), ("x",))


def _run(raw_fn, x, capacity=5):
    f = shard_map(
        lambda xs, lc, gc: raw_fn(xs[0], lc[0], gc[0], "x", capacity)[None],
        mesh=_mesh2(), in_specs=(P("x"), P("x"), P("x")),
        out_specs=P("x"))
    return np.asarray(f(jnp.asarray(x), jnp.asarray(LC), jnp.asarray(GC)))


def test_global_scatter_matches_reference_example():
    x = np.stack([X_ROWS, X_ROWS])
    out = _run(_global_scatter_raw, x)
    for rank in range(2):
        np.testing.assert_array_equal(out[rank][:5], SCATTER_EXPECTED[rank])
        # capacity padding past the valid rows is zero
        assert np.all(out[rank][5:] == 0)


def test_global_gather_matches_reference_example():
    x = np.stack([X_ROWS, X_ROWS])
    out = _run(_global_gather_raw, x)
    for rank in range(2):
        np.testing.assert_array_equal(out[rank][:5], GATHER_EXPECTED[rank])
        assert np.all(out[rank][5:] == 0)


def test_gather_inverts_scatter():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 5, 3)).astype(np.float32)

    def round_trip(xs, lc, gc):
        mid = _global_scatter_raw(xs[0], lc[0], gc[0], "x", 5)
        back = _global_gather_raw(mid, lc[0], gc[0], "x", 5)
        return back[None]

    f = shard_map(round_trip, mesh=_mesh2(),
                  in_specs=(P("x"), P("x"), P("x")), out_specs=P("x"))
    out = np.asarray(f(jnp.asarray(x), jnp.asarray(LC), jnp.asarray(GC)))
    for rank in range(2):
        np.testing.assert_allclose(out[rank][:5], x[rank], rtol=1e-6)


def test_global_scatter_grad_is_identity_permutation():
    """Each row is sent exactly once, so d(sum(out^2))/dx == 2x —
    the gradient printed in the reference docstring example."""
    x = np.stack([X_ROWS, X_ROWS])

    def loss(xs):
        f = shard_map(
            lambda s, lc, gc: _global_scatter_raw(
                s[0], lc[0], gc[0], "x", 5)[None],
            mesh=_mesh2(), in_specs=(P("x"), P("x"), P("x")),
            out_specs=P("x"))
        out = f(xs, jnp.asarray(LC), jnp.asarray(GC))
        return (out * out).sum()

    g = np.asarray(jax.grad(loss)(jnp.asarray(x)))
    np.testing.assert_allclose(g, 2.0 * x, rtol=1e-6)


def test_eager_single_controller_path():
    """world_size 1: card-major == expert-major, so dispatch is the
    identity on the first sum(counts) rows, with exact dynamic shape."""
    x = paddle.to_tensor(X_ROWS)
    x.stop_gradient = False
    lc = paddle.to_tensor(np.array([3, 2], np.int64))
    out = global_scatter(x, lc, lc)
    np.testing.assert_array_equal(out.numpy(), X_ROWS)
    back = global_gather(out, lc, lc)
    np.testing.assert_array_equal(back.numpy(), X_ROWS)
    (out * out).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 2.0 * X_ROWS)


def test_dispatch_dtype_validation():
    x = paddle.to_tensor(X_ROWS)
    bad_counts = paddle.to_tensor(np.array([3.0, 2.0], np.float32))
    with pytest.raises(TypeError):
        global_scatter(x, bad_counts, bad_counts)
    with pytest.raises(TypeError):
        global_gather(paddle.to_tensor(X_ROWS.astype(bool)),
                      paddle.to_tensor(np.array([3, 2], np.int64)),
                      paddle.to_tensor(np.array([3, 2], np.int64)))
