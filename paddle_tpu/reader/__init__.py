"""Legacy reader decorators.

Reference: python/paddle/reader/decorator.py (map_readers, buffered,
compose, chain, shuffle, firstn, xmap_readers). These predate paddle.io
but remain part of the public surface; implemented host-side (pure python
iterators feeding the device pipeline).
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = ['map_readers', 'buffered', 'compose', 'chain', 'shuffle',
           'firstn', 'xmap_readers', 'ComposeNotAligned']


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    def chained():
        yield from itertools.chain(*[r() for r in readers])
    return chained


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop('check_alignment', True)

    def _flatten(x):
        return x if isinstance(x, tuple) else (x,)

    _missing = object()

    def composed():
        rs = [r() for r in readers]
        for items in itertools.zip_longest(*rs, fillvalue=_missing):
            if _missing in items:
                if check_alignment:
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                items = tuple(i for i in items if i is not _missing)
            yield sum((_flatten(i) for i in items), ())
    return composed


def buffered(reader, size):
    """Prefetch up to ``size`` items in a background thread."""
    end = object()

    def buffered_reader():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            yield e
    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item
    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Parallel map over a reader with worker threads (order-preserving
    when ``order``)."""
    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            for i, d in enumerate(reader()):
                in_q.put((i, d))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, d = item
                try:
                    out_q.put((i, mapper(d)))
                except BaseException as e:  # propagate to the consumer
                    out_q.put(("__xmap_error__", e))
                    out_q.put(end)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            pending, want = {}, 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                i, v = item
                if i == "__xmap_error__":
                    raise v
                pending[i] = v
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if item[0] == "__xmap_error__":
                    raise item[1]
                yield item[1]
    return xreader
