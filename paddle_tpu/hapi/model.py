"""High-level Model API. Reference: python/paddle/hapi/model.py.

``Model.prepare/fit/evaluate/predict/save/load`` with the same surface; the
training loop compiles one fused XLA train step via
fleet.train_step.make_train_step (the reference's prepare() chooses between
dygraph/static executors — here the compiled path IS the default).
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..metric import Metric
from ..tensor import Tensor
from .callbacks import CallbackList, ProgBarLogger

class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        self._amp_level = None
        if amp_configs:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            elif isinstance(amp_configs, dict):
                self._amp_level = amp_configs.get("level", "O1")
        return self

    def _build_train_step(self):
        if self._train_step is not None:
            return self._train_step
        from ..distributed.fleet.train_step import make_train_step

        loss_layer = self._loss

        def loss_fn(network, *batch):
            *xs, y = batch
            out = network(*xs)
            return loss_layer(out, y)

        self._train_step = make_train_step(
            self.network, self._optimizer, loss_fn,
            amp_level=getattr(self, "_amp_level", None))
        return self._train_step

    # -- training ------------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        step = self._build_train_step()
        loss = step(*inputs, *labels)
        return [float(np.asarray(loss._data))]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        out = self.network(*inputs)
        res = []
        if self._loss is not None and labels:
            loss = self._loss(out, labels[0])
            res.append(float(np.asarray(loss._data)))
        metric_out = []
        for m in self._metrics:
            c = m.compute(out, *labels)
            metric_out.append(m.update(c))
        self.network.train()
        return res, metric_out

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        self.network.train()
        return out

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset

        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        cbks = CallbackList(callbacks, model=self, verbose=verbose,
                            metrics=["loss"] + sum(
                                [m.name() if isinstance(m.name(), list)
                                 else [m.name()] for m in self._metrics], []),
                            log_freq=log_freq)
        cbks.on_begin("train")
        steps = None
        try:
            steps = len(train_loader)
        except TypeError:
            pass
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch, steps)
            for m in self._metrics:
                m.reset()
            it = 0
            logs = {}
            for batch in train_loader:
                cbks.on_batch_begin("train", it, None)
                xs, ys = self._split_batch(batch)
                losses = self.train_batch(xs, ys)
                logs = {"loss": losses[0], "step": it}
                cbks.on_batch_end("train", it, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training:
                break
        cbks.on_end("train")
        if save_dir:
            self.save(os.path.join(save_dir, "final"))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        from ..io import DataLoader, Dataset
        loader = DataLoader(eval_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(eval_data, Dataset) else eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        it = 0
        for batch in loader:
            xs, ys = self._split_batch(batch)
            res, _ = self.eval_batch(xs, ys)
            if res:
                losses.append(res[0])
            it += 1
            if num_iters is not None and it >= num_iters:
                break
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for n, v in zip(names, vals):
                logs[n] = v
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset
        loader = DataLoader(test_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(test_data, Dataset) else test_data
        outputs = []
        k = self._n_inputs()  # signature parse once, not per batch
        for batch in loader:
            if isinstance(batch, (list, tuple)):
                xs = list(batch[:k]) if (k is not None
                                         and k < len(batch)) else list(batch)
            else:
                xs = [batch]
            outputs.append(self.predict_batch(xs))
        if stack_outputs and outputs:
            from ..tensor_ops.manipulation import concat
            return [concat(outputs, axis=0)]
        return outputs

    def _n_inputs(self):
        """How many leading batch elements are network inputs: declared
        InputSpecs win; otherwise the forward() MAX positional arity, so
        optional-but-real inputs (masks, initial states) are kept and only
        genuinely un-acceptable trailing elements (labels) are dropped."""
        if self._inputs is not None:
            specs = self._inputs if isinstance(self._inputs, (list, tuple)) \
                else [self._inputs]
            return len(specs)
        import inspect
        try:
            sig = inspect.signature(self.network.forward)
        except (TypeError, ValueError):
            return None
        n = 0
        for p in sig.parameters.values():
            if p.kind == p.VAR_POSITIONAL:
                return None  # *args: take the whole batch
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                n += 1
        return n or None

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return list(batch), []
        return [batch], []

    # -- io -----------------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as psave
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        psave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            psave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload
        self.network.set_state_dict(pload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(pload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        return summary(self.network, input_size, dtype)
