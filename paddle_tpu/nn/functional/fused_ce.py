"""Fused LM-head linear + cross entropy, chunked over the vocabulary.

Reference capability: fused softmax-cross-entropy kernels in
paddle/phi/kernels (softmax_with_cross_entropy) applied at the LM head.
TPU-native: the [N, V] fp32 logits tensor (1+ GB at pretraining shapes)
never materializes — a lax.scan walks vocab chunks computing an ONLINE
logsumexp and gathering the label logit; jax.checkpoint on the chunk body
recomputes chunk logits in the backward, so peak memory is O(N * chunk)
instead of O(N * V). Exact (not approximate): matches cross_entropy to
fp32 accumulation order.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...tensor import Tensor, apply


def _fused_raw(hidden, w, labels, chunk):
    """hidden [N, H] (any float dtype), w [H, V], labels [N] int -> scalar
    mean CE."""
    N, H = hidden.shape
    V = w.shape[1]
    nc = (V + chunk - 1) // chunk
    vp = nc * chunk
    if vp != V:
        w = jnp.pad(w, ((0, 0), (0, vp - V)))
    wc = w.reshape(H, nc, chunk).transpose(1, 0, 2)  # [nc, H, chunk]
    labels = labels.astype(jnp.int32)

    def body(carry, args):
        m, s, lab_logit = carry
        w_c, off = args
        logits = jnp.dot(hidden, w_c,
                         preferred_element_type=jnp.float32)  # [N, chunk]
        col = off + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        valid = col < V
        logits = jnp.where(valid, logits, -jnp.inf)
        m_c = jnp.max(logits, axis=1)
        m_new = jnp.maximum(m, m_c)
        # guard exp(-inf - -inf): rows are never fully masked after chunk 0
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        in_chunk = jnp.logical_and(labels >= off, labels < off + chunk)
        idx = jnp.clip(labels - off, 0, chunk - 1)
        picked = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        lab_logit = lab_logit + jnp.where(in_chunk, picked, 0.0)
        return (m_new, s, lab_logit), None

    m0 = jnp.full((N,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((N,), jnp.float32)
    l0 = jnp.zeros((N,), jnp.float32)
    offs = jnp.arange(nc, dtype=jnp.int32) * chunk
    (m, s, lab_logit), _ = jax.lax.scan(
        jax.checkpoint(body), (m0, s0, l0), (wc, offs))
    nll = jnp.log(s) + m - lab_logit
    return jnp.mean(nll)


def fused_linear_cross_entropy(hidden, weight, labels, chunk_size: int = 8192,
                               name=None):
    """mean CE of ``hidden @ weight`` against int ``labels`` without
    materializing the [N, V] logits. hidden: [..., H] Tensor; weight:
    [H, V]; labels: [...] int."""
    def f(h, w, lab):
        h2 = h.reshape(-1, h.shape[-1])
        return _fused_raw(h2, w, lab.reshape(-1), int(chunk_size))

    return apply(f, hidden, weight, labels)
