"""Request admission for the serving engine.

Two schedulers share the guard machinery: :class:`FIFOScheduler`
(strict arrival order) and :class:`PriorityScheduler` (the Engine
default — priority classes with EDF deadline ordering within a class,
falling back to exact FIFO behavior for all-default traffic).

FIFO with two guards:

- **token-budget watermark** — the sum of ``prompt_len + max_new_tokens``
  over in-flight requests stays under ``token_budget``; the queue head
  waits (strict FIFO, no head-of-line skipping) until enough slots drain.
  Keeps worst-case KV residency bounded independent of n_slots.
- **queue-depth backpressure** — ``enqueue`` raises EngineOverloaded once
  ``max_queue`` requests are waiting; callers shed load instead of
  growing an unbounded host-side queue.

Admission order is a pure function of arrival order (deque + watermark,
no timestamps), which together with per-request PRNG chains makes every
request's output independent of co-batched traffic.
"""
from __future__ import annotations

import collections


class EngineOverloaded(RuntimeError):
    """Raised by submit() when the waiting queue is at max_queue depth.

    ``retry_after_s`` (when the engine has decode-latency history) is
    the estimated seconds until a slot frees — clients should back off
    at least that long before resubmitting. ``replica`` names the fleet
    replica that refused the request (None standalone; None also on a
    fleet-wide rejection, where EVERY replica was browned out).
    """

    def __init__(self, message, retry_after_s=None, replica=None):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.replica = replica


class FIFOScheduler:
    def __init__(self, token_budget, max_queue):
        if token_budget < 1:
            raise ValueError("token_budget must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.token_budget = int(token_budget)
        self.max_queue = int(max_queue)
        self._queue = collections.deque()
        self._inflight_tokens = 0

    @staticmethod
    def _load(handle):
        return handle.n_prompt + handle.max_new_tokens

    @staticmethod
    def _immediate(handle):
        """Token lines the request needs the moment it admits: prompt +
        already-emitted replay tokens + the first decode write line.
        The paged engine gates admission on this against the pool's
        free-block headroom (``free_tokens``) instead of reserving the
        worst case — decode growth allocates lazily, preemption covers
        the tail risk."""
        return (handle.n_prompt + len(getattr(handle, "tokens", ())) + 1)

    @property
    def queue_depth(self):
        return len(self._queue)

    @property
    def inflight_tokens(self):
        return self._inflight_tokens

    def enqueue(self, handle, retry_after_s=None):
        if len(self._queue) >= self.max_queue:
            hint = ("" if retry_after_s is None
                    else f" ~{retry_after_s}s (current inter-token "
                         f"latency x shortest active request)")
            raise EngineOverloaded(
                f"serving queue full ({self.max_queue} waiting); retry "
                f"after{hint or ' the engine drains'}",
                retry_after_s=retry_after_s,
                replica=getattr(handle, "replica_id", None))
        self._queue.append(handle)

    def drop_expired(self, now):
        """Remove and return queued handles whose deadline passed while
        they waited — they never held a slot or token-budget share, so
        nothing is released."""
        expired = [h for h in self._queue
                   if getattr(h, "deadline", None) is not None
                   and now > h.deadline]
        if expired:
            dead = set(map(id, expired))
            self._queue = collections.deque(
                h for h in self._queue if id(h) not in dead)
        return expired

    def pop_admissible(self, free_slots, free_tokens=None):
        """Pop the FIFO prefix that fits in ``free_slots``, the token
        watermark, and (when given) ``free_tokens`` — the paged pool's
        free-block headroom in token lines, so admission accounts FREE
        BLOCKS, not worst-case slot reservations. Popped handles are
        counted in-flight immediately; call release() when their request
        finishes."""
        out = []
        while self._queue and free_slots > 0:
            head = self._queue[0]
            need = self._load(head)
            if self._inflight_tokens + need > self.token_budget and \
                    self._inflight_tokens > 0:
                break   # strict FIFO: head waits, nothing overtakes it
            if free_tokens is not None:
                imm = self._immediate(head)
                if imm > free_tokens:
                    break   # head waits for blocks; nothing overtakes
                free_tokens -= imm
            out.append(self._queue.popleft())
            self._inflight_tokens += need
            free_slots -= 1
        return out

    def requeue(self, handle):
        """Put a preempted (or pool-bounced) handle back at the front of
        arrival order, bypassing max_queue backpressure — it was already
        admitted once and holds no budget share while queued."""
        self._queue.appendleft(handle)

    def remove(self, handle):
        """Drop one queued handle (client abandon). Queued handles hold
        no budget share, so nothing is released. True if it was
        queued."""
        try:
            self._queue.remove(handle)
            return True
        except ValueError:
            return False

    def shed_lowest(self, protect_priority=0):
        """Brownout eviction: remove and return every queued handle of
        the single lowest-priority class present among priorities
        strictly above ``protect_priority`` — the least important work
        goes first, one class at a time, and protected classes are
        never shed."""
        worst = max((getattr(h, "priority", 0) for h in self._queue
                     if getattr(h, "priority", 0) > protect_priority),
                    default=None)
        if worst is None:
            return []
        out = [h for h in self._queue
               if getattr(h, "priority", 0) == worst]
        for h in out:
            self._queue.remove(h)
        return out

    def release(self, handle):
        self._inflight_tokens -= self._load(handle)


class PriorityScheduler(FIFOScheduler):
    """Priority classes + deadline-aware (EDF) admission.

    Queued requests admit in ``(priority, deadline, arrival)`` order: a
    lower priority number always admits first; within a class, requests
    carrying wall-clock deadlines run earliest-deadline-first (they are
    exactly the ones overload would expire while they wait), and
    deadline-less requests keep strict FIFO arrival order behind them.
    The token watermark applies to the sorted head exactly as in the
    FIFO base: the most urgent waiting request blocks admission rather
    than being overtaken, so a class can never starve its own head.
    With all-default priorities and no deadlines this degenerates to
    strict FIFO — the Engine default costs nothing.
    """

    @staticmethod
    def _key(h):
        d = getattr(h, "deadline", None)
        return (getattr(h, "priority", 0),
                d if d is not None else float("inf"),
                getattr(h, "request_id", 0))

    def pop_admissible(self, free_slots, free_tokens=None):
        out = []
        while self._queue and free_slots > 0:
            head = min(self._queue, key=self._key)
            need = self._load(head)
            if self._inflight_tokens + need > self.token_budget and \
                    self._inflight_tokens > 0:
                break   # the most urgent request waits; nothing overtakes
            if free_tokens is not None:
                imm = self._immediate(head)
                if imm > free_tokens:
                    break   # urgent head waits for blocks; no overtaking
                free_tokens -= imm
            self._queue.remove(head)
            out.append(head)
            self._inflight_tokens += need
            free_slots -= 1
        return out
