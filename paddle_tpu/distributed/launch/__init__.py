"""python -m paddle_tpu.distributed.launch — reference CLI spelling
(python -m paddle.distributed.launch) for the supervised launcher in
launch_main.py."""
from ..launch_main import main  # noqa: F401
