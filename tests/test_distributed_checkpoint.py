"""Sharded async checkpoint save/restore on an 8-device mesh.

Reference: incubate/checkpoint + fleet checkpoint utils — the contract
verified here: per-shard async save; restore resharded onto a (different)
mesh sharding via template; step manager retention.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.checkpoint import (
    CheckpointManager, load_distributed, save_distributed,
    wait_for_checkpoints)
from paddle_tpu.distributed.mesh import build_mesh

pytestmark = pytest.mark.skipif(
    not os.environ.get("JAX_PLATFORMS", "").startswith("cpu"),
    reason="needs the 8-device CPU mesh")


def _state(mesh):
    w = jax.device_put(np.arange(64, dtype=np.float32).reshape(8, 8),
                       NamedSharding(mesh, P("dp", "tp")))
    m = jax.device_put(np.ones((8, 8), np.float32) * 2,
                       NamedSharding(mesh, P("sharding", None)))
    return {"params": {"w": w}, "opt": {"w": {"moment1": m}},
            "step": jnp.int32(7)}


def test_sharded_roundtrip_resharded(tmp_path):
    mesh = build_mesh(dp=2, tp=2, sharding=2)
    state = _state(mesh)
    path = save_distributed(state, tmp_path / "ck", async_save=False)

    # restore with a DIFFERENT target sharding (resharded load)
    tmpl = {
        "params": {"w": jax.ShapeDtypeStruct(
            (8, 8), jnp.float32,
            sharding=NamedSharding(mesh, P("tp", None)))},
        "opt": {"w": {"moment1": jax.ShapeDtypeStruct(
            (8, 8), jnp.float32,
            sharding=NamedSharding(mesh, P(None, "dp")))}},
        "step": jax.ShapeDtypeStruct((), jnp.int32),
    }
    out = load_distributed(path, tmpl)
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]),
        np.arange(64, dtype=np.float32).reshape(8, 8))
    np.testing.assert_array_equal(np.asarray(out["opt"]["w"]["moment1"]),
                                  np.full((8, 8), 2.0, np.float32))
    assert int(out["step"]) == 7
    got = out["params"]["w"].sharding
    assert isinstance(got, NamedSharding) and got.spec == P("tp", None)


def test_async_save_then_wait(tmp_path):
    mesh = build_mesh(dp=2, tp=2, sharding=2)
    state = _state(mesh)
    path = save_distributed(state, tmp_path / "ck_async", async_save=True)
    wait_for_checkpoints()
    out = load_distributed(path, _state(mesh))
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]._data
                   if hasattr(out["params"]["w"], "_data")
                   else out["params"]["w"]),
        np.arange(64, dtype=np.float32).reshape(8, 8))
    # orbax wrote a real checkpoint directory with per-array metadata
    assert os.path.isdir(path)


def test_manager_retention_and_latest(tmp_path):
    mesh = build_mesh(dp=2, tp=2, sharding=2)
    mgr = CheckpointManager(tmp_path / "run", max_to_keep=2)
    for step in (1, 2, 3):
        st = {"w": jax.device_put(
            np.full((4,), float(step), np.float32),
            NamedSharding(mesh, P(None)))}
        mgr.save(step, st, async_save=False)
    assert mgr.latest_step() == 3
    assert len(mgr.all_steps()) <= 2
    step, out = mgr.restore_latest(
        {"w": jax.ShapeDtypeStruct((4,), jnp.float32,
                                   sharding=NamedSharding(mesh, P(None)))})
    assert step == 3
    np.testing.assert_array_equal(np.asarray(out["w"]), [3.0] * 4)
