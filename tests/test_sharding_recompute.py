"""paddle.distributed.sharding (group_sharded_parallel) and
fleet.utils (recompute / LocalFS / DistributedInfer).

References: python/paddle/distributed/sharding/group_sharded.py:40,176;
distributed/fleet/utils/recompute.py:350; fleet/utils/fs.py:120.
"""
import dataclasses
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.distributed import fleet, sharding


def test_recompute_grad_parity():
    paddle.seed(0)
    block = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 8))
    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((4, 8)).astype(np.float32))
    out1 = fleet.utils.recompute(block, x)
    (out1 ** 2).mean().backward()
    g1 = {k: np.asarray(p.grad._data)
          for k, p in block.named_parameters()}
    for p in block.parameters():
        p.clear_grad()
    out2 = block(x)
    np.testing.assert_allclose(out1.numpy(), out2.numpy(), atol=1e-6)
    (out2 ** 2).mean().backward()
    for k, p in block.named_parameters():
        np.testing.assert_allclose(g1[k], np.asarray(p.grad._data),
                                   atol=1e-6, err_msg=k)


def test_recompute_recomputes_in_backward():
    """The recompute segment's grad program must re-run the forward
    (extra matmul) instead of saving hidden activations: the remat
    primitive appears in the vjp jaxpr and the backward holds one more
    dot than the non-checkpointed vjp."""
    import jax
    import jax.numpy as jnp

    w1 = jnp.ones((8, 16))
    w2 = jnp.ones((16, 8))

    def seg(x):
        return jnp.tanh(x @ w1) @ w2

    x = jnp.ones((2, 8))

    def bwd_jaxpr(fn):
        def run(x):
            out, vjp = jax.vjp(fn, x)
            return vjp(jnp.ones_like(out))
        return str(jax.make_jaxpr(run)(x))

    plain = bwd_jaxpr(seg)
    ck = bwd_jaxpr(jax.checkpoint(seg))
    assert "remat" in ck and "remat" not in plain
    assert ck.count("dot_general") == plain.count("dot_general") + 1


@pytest.mark.slow   # unblocked by the PR-12 Tensor-pytree fix; ~9s of
# stage-2/3 group-sharded compiles — slow lane per the tier-1 budget
def test_group_sharded_parallel_levels(monkeypatch):
    # fresh-process semantics: earlier tests in the suite may leave a
    # non-trivial fleet topology active, which the API (correctly)
    # refuses to clobber; monkeypatch restores the prior state after
    import paddle_tpu.distributed.fleet as _fleet
    import paddle_tpu.distributed.mesh as _mesh

    monkeypatch.setattr(_fleet, "_strategy", None)
    monkeypatch.setattr(_fleet, "_hcg", None)
    monkeypatch.setattr(_mesh, "_global_mesh", _mesh._global_mesh)
    paddle.seed(0)
    from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

    cfg = dataclasses.replace(LLAMA_TINY, dtype="float32")
    model = LlamaForCausalLM(cfg)
    opt = optim.AdamW(learning_rate=1e-3,
                      parameters=model.parameters())
    with pytest.raises(ValueError):
        sharding.group_sharded_parallel(model, opt, "bogus")
    model, opt, scaler = sharding.group_sharded_parallel(
        model, opt, "p_g_os")
    assert scaler is None
    step = opt.make_train_step(model, lambda m, i, l: m(i, labels=l))
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32))
    lbl = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32))
    l0 = float(np.asarray(step(ids, lbl)._data))
    l1 = l0
    for _ in range(3):
        l1 = float(np.asarray(step(ids, lbl)._data))
    assert np.isfinite(l0) and l1 < l0
    specs = {str(p._data.sharding.spec) for p in model.parameters()}
    assert any("sharding" in s for s in specs)  # ZeRO-3 param placement

    with tempfile.TemporaryDirectory() as td:
        sharding.save_group_sharded_model(model, td, opt)
        assert os.path.exists(os.path.join(td, "model.pdparams"))
        assert os.path.exists(os.path.join(td, "model.pdopt"))
        state = paddle.load(os.path.join(td, "model.pdparams"))
        assert len(state) == len(dict(model.named_parameters()))


def test_local_fs_roundtrip(tmp_path):
    fs = fleet.utils.LocalFS()
    base = str(tmp_path)
    fs.mkdirs(os.path.join(base, "d1/d2"))
    fs.touch(os.path.join(base, "d1/a.txt"))
    dirs, files = fs.ls_dir(os.path.join(base, "d1"))
    assert dirs == ["d2"] and files == ["a.txt"]
    assert fs.is_dir(os.path.join(base, "d1"))
    assert fs.is_file(os.path.join(base, "d1/a.txt"))
    fs.mv(os.path.join(base, "d1/a.txt"), os.path.join(base, "d1/b.txt"))
    assert fs.is_exist(os.path.join(base, "d1/b.txt"))
    fs.delete(os.path.join(base, "d1"))
    assert not fs.is_exist(os.path.join(base, "d1"))
    assert fs.list_dirs(base) == []
    assert not fs.need_upload_download()


def test_hdfs_client_requires_hadoop():
    import shutil

    if shutil.which("hadoop"):
        pytest.skip("hadoop present")
    with pytest.raises(RuntimeError):
        fleet.utils.HDFSClient()


def test_distributed_infer_shim():
    paddle.seed(0)
    net = nn.Linear(4, 2)
    di = fleet.utils.DistributedInfer()
    m = di.get_dygraph_infer_model(net)
    assert not m.training


def test_recompute_closure_and_bound_method_grads():
    """Wrapping the layer in a lambda / bound method must still route
    parameter gradients (silent zero-grad regression)."""
    paddle.seed(0)
    blk = nn.Sequential(nn.Linear(6, 12), nn.Tanh(), nn.Linear(12, 6))
    x = paddle.to_tensor(np.random.default_rng(1)
                         .standard_normal((3, 6)).astype(np.float32))

    def ref_grads():
        for p in blk.parameters():
            p.clear_grad()
        (blk(x) ** 2).mean().backward()
        return {k: np.asarray(p.grad._data)
                for k, p in blk.named_parameters()}

    expected = ref_grads()
    for wrap in (lambda t: blk(t), blk.forward):
        for p in blk.parameters():
            p.clear_grad()
        out = fleet.utils.recompute(wrap, x)
        (out ** 2).mean().backward()
        for k, p in blk.named_parameters():
            assert p.grad is not None, k
            np.testing.assert_allclose(
                np.asarray(p.grad._data), expected[k], atol=1e-6,
                err_msg=f"{wrap}: {k}")


def test_local_fs_mv_overwrite_replaces_dir(tmp_path):
    fs = fleet.utils.LocalFS()
    src = tmp_path / "new"
    dst = tmp_path / "old"
    src.mkdir()
    dst.mkdir()
    (src / "f.txt").write_text("new")
    (dst / "stale.txt").write_text("old")
    fs.mv(str(src), str(dst), overwrite=True)
    assert (dst / "f.txt").exists()
    assert not (dst / "stale.txt").exists()  # replaced, not nested
    assert not (dst / "new").exists()


def test_recompute_partial_and_nontensor_args():
    """functools.partial wrapping and non-tensor positional args
    (None / ints) must work with full gradient routing."""
    import functools

    paddle.seed(0)
    blk = nn.Linear(6, 6)
    x = paddle.to_tensor(np.random.default_rng(5)
                         .standard_normal((2, 6)).astype(np.float32))

    def run(layer, t, mask, scale):
        out = layer(t) * scale
        if mask is not None:
            out = out * mask
        return out

    for p in blk.parameters():
        p.clear_grad()
    out = fleet.utils.recompute(functools.partial(run, blk),
                                x, None, 2.0)
    (out ** 2).mean().backward()
    g1 = {k: np.asarray(p.grad._data)
          for k, p in blk.named_parameters()}
    assert all(np.abs(v).max() > 0 for v in g1.values())

    for p in blk.parameters():
        p.clear_grad()
    (run(blk, x, None, 2.0) ** 2).mean().backward()
    for k, p in blk.named_parameters():
        np.testing.assert_allclose(g1[k], np.asarray(p.grad._data),
                                   atol=1e-6, err_msg=k)


def test_recompute_layer_as_positional_arg():
    """A Layer passed positionally (not closed over) must still get
    gradients routed through the checkpoint."""
    paddle.seed(0)
    blk = nn.Linear(6, 6)
    x = paddle.to_tensor(np.random.default_rng(6)
                         .standard_normal((2, 6)).astype(np.float32))
    out = fleet.utils.recompute(lambda layer, t: layer(t), blk, x)
    (out ** 2).mean().backward()
    for k, p in blk.named_parameters():
        assert p.grad is not None, k
        assert np.abs(np.asarray(p.grad._data)).max() > 0, k
