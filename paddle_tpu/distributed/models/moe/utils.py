"""MoE routing primitives (reference distributed/models/moe/utils.py —
there each is a CUDA custom op; here plain jnp, jit-able, same
semantics)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....tensor import Tensor


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap_like(val, ref):
    return Tensor(val) if isinstance(ref, Tensor) else val


def _number_count(numbers, upper_range):
    """How many routed ids fall on each expert: bincount over the
    flattened gate indices (reference utils.py:22 number_count op)."""
    raw = _raw(numbers).reshape(-1)
    out = jnp.bincount(raw, length=int(upper_range)).astype(jnp.int64)
    return _wrap_like(out, numbers)


def _assign_pos(x, cum_count):
    """Slot each routed id into its expert's contiguous region
    (reference utils.py:61 assign_pos op): for ids x (flattened in
    routing order) and inclusive cumulative expert counts ``cum_count``,
    returns pos such that pos[j] = the routing-order index of the j-th
    token when tokens are grouped by expert (stable within an expert).

    Matches the reference example: number_count=[2,0,2,0],
    numbers=[[0,2],[0,2]] → pos=[2,0,3,1] — i.e. the op fills each
    expert's region back-to-front over the reversed scan order.
    """
    ids = _raw(x).reshape(-1)
    cum = _raw(cum_count).astype(jnp.int32)
    # reference kernel: iterate tokens, pos[--cum[e]] = token_index;
    # equivalently a stable sort by expert with within-expert order
    # REVERSED (the kernel decrements from the region end)
    n = ids.shape[0]
    rev = ids[::-1]
    order = jnp.argsort(rev, stable=True)        # group reversed ids
    pos = (n - 1) - order                        # back to original idx
    out = pos.astype(cum.dtype)
    return _wrap_like(out, x)


def _random_routing(topk_idx, topk_value, prob, topk=2):
    """Stochastically drop second-choice experts (reference utils.py:111):
    keep choice 2 only where prob < 2 * gate_value, else route to -1
    (dropped)."""
    if topk != 2:
        raise ValueError("only topk=2 supported (reference parity)")
    idx = _raw(topk_idx)
    val = _raw(topk_value)
    p = _raw(prob)
    keep = p < 2.0 * val[:, 1]
    new_second = jnp.where(keep, idx[:, 1], -1)
    out = jnp.stack([idx[:, 0], new_second], axis=1)
    return _wrap_like(out, topk_idx)


def _limit_by_capacity(expert_count, capacity, n_worker):
    """Clamp per-(worker, expert) counts so each expert's global total
    stays under its capacity (reference utils.py:136): workers take
    capacity greedily in worker order."""
    ec = _raw(expert_count).reshape(int(n_worker), -1)  # [W, E]
    cap = _raw(capacity).astype(ec.dtype)               # [E]

    def body(remaining, row):
        take = jnp.minimum(row, remaining)
        return remaining - take, take

    _, taken = jax.lax.scan(body, cap, ec)
    out = taken.reshape(-1)
    return _wrap_like(out, expert_count)


def _prune_gate_by_capacity(gate_idx, expert_count, n_expert, n_worker):
    """Re-route tokens of over-capacity experts to -1 (reference
    utils.py:180): the first ``expert_count[e]`` tokens routed to expert
    e keep their assignment, later ones are dropped."""
    idx = _raw(gate_idx).reshape(-1)
    ec = _raw(expert_count).reshape(-1)
    E = int(n_expert) * int(n_worker)
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)
    pos_in_e = jnp.cumsum(onehot, axis=0) - onehot      # [N, E]
    my_pos = jnp.take_along_axis(pos_in_e, idx[:, None], axis=1)[:, 0]
    keep = my_pos < ec[idx]
    out = jnp.where(keep, idx, -1).astype(_raw(gate_idx).dtype)
    return _wrap_like(out.reshape(_raw(gate_idx).shape), gate_idx)
