"""Eager dispatch fast path: signature-keyed fwd/vjp compile cache.

Covers the PR-3 tentpole: steady-state eager execution is trace-free
(retrace-count regression), bit-identical to the uncached path, and the
safety rails hold — hooks, inplace ops, no_grad, double-backward via
autograd.functional, randomness bypass, data-dependent-op blacklisting,
bounded LRU, invalidation, and the fused optimizer micro-step.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import dispatch_cache as dc


@pytest.fixture(autouse=True)
def _cache_on():
    """Cache on with the engage thresholds floored: the production
    defaults (32 sightings / 32 optimizer steps) exist so short loops
    never pay a compile, but these tests WANT the compiled path inside
    a handful of iterations."""
    from paddle_tpu.optimizer import optimizer as opt_mod

    prev = dc.enabled()
    prev_warm = dc.set_warmup(2)
    prev_fused = opt_mod._FUSED_WARMUP
    opt_mod._FUSED_WARMUP = 0
    dc.set_enabled(True)
    dc.reset_stats()
    yield
    dc.set_enabled(prev)
    dc.set_warmup(prev_warm)
    opt_mod._FUSED_WARMUP = prev_fused


def _mlp_and_opt(hidden=16):
    paddle.seed(0)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, hidden), paddle.nn.ReLU(),
        paddle.nn.Linear(hidden, 4))
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    return net, opt


def _train(steps, hidden=16, opt_factory=None, enabled=True):
    dc.set_enabled(enabled)
    paddle.seed(0)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, hidden), paddle.nn.ReLU(),
        paddle.nn.Linear(hidden, 4))
    opt = (opt_factory(net.parameters()) if opt_factory else
           paddle.optimizer.Adam(learning_rate=1e-3,
                                 parameters=net.parameters()))
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 4, (8,)).astype(np.int64))
    losses = []
    for _ in range(steps):
        loss = paddle.nn.functional.cross_entropy(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    dc.set_enabled(True)
    return losses


def test_steady_state_is_trace_free():
    """Retrace-count regression: after warmup, a fixed-shape eager train
    loop must be 100% cache hits — 0 misses/compiles/bypasses."""
    net, opt = _mlp_and_opt()
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    y = paddle.to_tensor(np.zeros((4,), np.int64))

    def step():
        loss = paddle.nn.functional.cross_entropy(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()

    for _ in range(3):  # warmup: miss, compile, hit
        step()
    before = dc.dispatch_stats()
    for _ in range(5):
        step()
    after = dc.dispatch_stats()
    assert after["misses"] == before["misses"]
    assert after["compiles"] == before["compiles"]
    assert after["bypasses"] == before["bypasses"]
    assert after["hits"] > before["hits"]


@pytest.mark.parametrize("opt_factory", [
    lambda ps: paddle.optimizer.Adam(learning_rate=1e-3, parameters=ps),
    lambda ps: paddle.optimizer.SGD(learning_rate=0.1, parameters=ps),
    lambda ps: paddle.optimizer.AdamW(learning_rate=1e-3, parameters=ps),
    lambda ps: paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                         parameters=ps),
], ids=["adam", "sgd", "adamw", "momentum"])
def test_bit_identical_losses_cache_on_vs_off(opt_factory):
    off = _train(6, opt_factory=opt_factory, enabled=False)
    on = _train(6, opt_factory=opt_factory, enabled=True)
    assert off == on  # bitwise, not allclose


def test_bit_identical_with_weight_decay_and_grad_clip():
    def mk(ps):
        return paddle.optimizer.Adam(
            learning_rate=1e-3, parameters=ps, weight_decay=1e-4,
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    assert _train(5, opt_factory=mk, enabled=False) == \
        _train(5, opt_factory=mk, enabled=True)


def test_grads_match_uncached_bitwise():
    def grads(enabled):
        dc.set_enabled(enabled)
        paddle.seed(0)
        x = paddle.to_tensor(np.linspace(-2, 2, 12).astype(np.float32)
                             .reshape(3, 4), stop_gradient=False)
        for _ in range(3):  # repeat so the cached path actually engages
            x.clear_grad()
            y = paddle.tanh(paddle.matmul(x, x.T)).sum()
            y.backward()
        out = x.grad.numpy().copy()
        dc.set_enabled(True)
        return out
    a, b = grads(False), grads(True)
    assert (a == b).all()


def test_hooks_fire_and_can_replace_grad():
    """Tensor hooks run eagerly between cached segments: the hook return
    value replaces the cotangent exactly as on the uncached path."""
    calls = []

    def hook(g):
        calls.append(g.numpy().copy())
        return g * 2.0

    def run(enabled, with_hook):
        dc.set_enabled(enabled)
        x = paddle.to_tensor(np.arange(4, dtype=np.float32),
                             stop_gradient=False)
        h = x.register_hook(hook) if with_hook else None
        for _ in range(3):
            x.clear_grad()
            (x * x).sum().backward()
        if h is not None:
            h.remove()
        out = x.grad.numpy().copy()
        dc.set_enabled(True)
        return out

    plain = run(True, False)
    hooked = run(True, True)
    assert calls and np.array_equal(hooked, 2.0 * plain)
    assert np.array_equal(hooked, run(False, True))


def test_inplace_ops_unaffected():
    def run(enabled):
        dc.set_enabled(enabled)
        outs = []
        for _ in range(3):
            t = paddle.to_tensor(np.full((3, 3), 2.0, np.float32))
            t.scale_(3.0)
            t.add_(paddle.to_tensor(np.ones((3, 3), np.float32)))
            outs.append(t.numpy().copy())
        dc.set_enabled(True)
        return outs
    a, b = run(False), run(True)
    assert all((x == y).all() for x, y in zip(a, b))
    assert (a[0] == 7.0).all()


def test_no_grad_uses_plain_forward():
    x = paddle.to_tensor(np.ones((4, 4), np.float32), stop_gradient=False)
    with paddle.no_grad():
        for _ in range(3):
            y = paddle.matmul(x, x)
    assert y._node is None and y.stop_gradient
    y2 = paddle.matmul(x, x)  # same op taped outside no_grad
    assert y2._node is not None
    assert np.array_equal(y.numpy(), y2.numpy())


def test_double_backward_via_autograd_functional():
    """functional-mode transforms trace straight through (tracer inputs
    bypass the cache) and stay correct while eager caching is live."""
    def f(x):
        return (x ** 3).sum()

    x = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
    for _ in range(2):
        g = paddle.autograd.grad(f)(x)
        h = paddle.autograd.hessian(f, x)
    assert np.allclose(g.numpy(), 3.0 * x.numpy() ** 2)
    hm = np.asarray(h[:, :])
    assert np.allclose(np.diag(hm), 6.0 * x.numpy())


def test_randomness_not_baked_into_cache():
    """dropout closes over a fresh PRNG key: the signature must bypass,
    never replay one mask from a compiled entry."""
    paddle.seed(7)
    x = paddle.to_tensor(np.ones((64, 64), np.float32))
    masks = [paddle.nn.functional.dropout(x, 0.5, training=True).numpy()
             for _ in range(4)]
    assert any(not np.array_equal(masks[0], m) for m in masks[1:])


def test_data_dependent_op_blacklisted_not_broken():
    """An op whose python body branches on values fails its first trace
    and must permanently fall back to the uncached path."""
    from paddle_tpu.tensor import apply

    def weird(a):
        if float(np.asarray(a).sum()) > 0:  # concretizes: untraceable
            return a * 2.0
        return a * -2.0

    # no-grad dispatch: the uncached path runs the python body eagerly
    # (the value branch is fine there); the cached attempt must fail its
    # trace, blacklist the op, and keep falling back
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    outs = [apply(weird, x).numpy() for _ in range(4)]
    assert all((o == 2.0).all() for o in outs)


def test_lru_is_bounded():
    before = dc.dispatch_stats()
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    for i in range(30):
        for _ in range(2):  # second sight promotes to a compiled entry
            paddle.scale(x, scale=1.0 + i)
    after = dc.dispatch_stats()
    assert after["entries"] <= after["capacity"]
    assert after["compiles"] > before["compiles"]


def test_megamorphic_op_stops_compiling():
    """Shape-churning ops (decode loops) must not compile one entry per
    shape forever."""
    from paddle_tpu.framework.dispatch_cache import _POLY_LIMIT
    before = dc.dispatch_stats()
    for n in range(2, _POLY_LIMIT + 12):
        x = paddle.to_tensor(np.ones((n, 3), np.float32))
        for _ in range(3):
            paddle.tanh(x)
    after = dc.dispatch_stats()
    assert after["compiles"] - before["compiles"] <= _POLY_LIMIT
    assert after["bypasses"] > before["bypasses"]


def test_invalidate_on_hook_registration():
    x = paddle.to_tensor(np.ones(3, np.float32))
    for _ in range(2):
        paddle.exp(x)
    before = dc.dispatch_stats()
    t = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    t.register_hook(lambda g: g)
    after = dc.dispatch_stats()
    assert after["invalidations"] == before["invalidations"] + 1
    assert after["entries"] == 0


def test_env_opt_out(tmp_path):
    """PADDLE_TPU_EAGER_CACHE=0 disables the cache at import time."""
    import subprocess
    import sys
    code = (
        "import numpy as np, paddle_tpu as paddle\n"
        "from paddle_tpu.framework import dispatch_cache as dc\n"
        "assert not dc.enabled()\n"
        "x = paddle.to_tensor(np.ones((2,2), np.float32))\n"
        "for _ in range(4): paddle.tanh(x)\n"
        "s = dc.dispatch_stats()\n"
        "assert s['hits'] == s['misses'] == s['compiles'] == 0, s\n"
        "print('OK')\n")
    env = dict(__import__("os").environ,
               PADDLE_TPU_EAGER_CACHE="0", JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0 and "OK" in out.stdout, out.stderr


def test_dispatch_stats_surfaced_through_framework_and_profiler():
    s1 = paddle.framework.dispatch_stats()
    s2 = paddle.profiler.dispatch_counters()
    for k in ("hits", "misses", "compiles", "bypasses", "enabled",
              "entries", "capacity"):
        assert k in s1 and k in s2


def test_fused_step_state_dict_snapshots_stay_alive():
    """The fused optimizer update must not kill buffers the user still
    holds through state_dict() (eager aliasing; donation is opt-in)."""
    net, opt = _mlp_and_opt()
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    y = paddle.to_tensor(np.zeros((4,), np.int64))
    loss = paddle.nn.functional.cross_entropy(net(x), y)
    loss.backward()
    opt.step()
    snap = opt.state_dict()
    opt.clear_grad()
    loss = paddle.nn.functional.cross_entropy(net(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    for k, v in snap.items():
        if isinstance(v, dict):
            for t in v.values():
                np.asarray(t._data)  # raises if the buffer was donated


def test_retain_graph_double_backward_still_works():
    x = paddle.to_tensor(np.asarray([2.0, 3.0], np.float32),
                         stop_gradient=False)
    for _ in range(3):
        x.clear_grad()
        y = (x * x).sum()
        y.backward(retain_graph=True)
        y.backward()
    assert np.allclose(x.grad.numpy(), 2 * 2 * x.numpy())
