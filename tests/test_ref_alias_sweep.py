"""Exhaustive sweep of the registered reference-path alias modules:
every _LazyAlias in sys.modules must import, and every name it declares
(its `names` restriction set) must resolve against its backing modules.
This pins the alias table so a backing-module rename breaks loudly.
"""
import importlib
import sys

import paddle_tpu  # noqa: F401 (registers all aliases)


def _alias_modules():
    from paddle_tpu.ref_alias import _LazyAlias

    return {name: mod for name, mod in list(sys.modules.items())
            if isinstance(mod, _LazyAlias)}


def test_every_alias_backing_imports():
    mods = _alias_modules()
    assert len(mods) > 80, f"expected a large alias table, got {len(mods)}"
    failures = []
    for name, mod in mods.items():
        try:
            mod._load()  # actually imports the backing module(s)
        except Exception as e:
            failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, f"alias backing modules broken: {failures}"


def test_every_declared_name_resolves():
    failures = []
    for name, mod in _alias_modules().items():
        declared = mod.__dict__.get("_names")
        if not declared:
            continue
        for attr in declared:
            try:
                getattr(mod, attr)
            except AttributeError:
                failures.append(f"{name}.{attr}")
    assert not failures, f"declared alias names missing: {failures}"


def test_unrestricted_aliases_have_live_backing():
    # names=None aliases forward everything; their backing modules must
    # at least import and expose a public surface
    empties = []
    for name, mod in _alias_modules().items():
        if mod.__dict__.get("_names"):
            continue
        try:
            backs = mod._load()
        except Exception as e:
            empties.append(f"{name}: backing import failed ({e})")
            continue
        if not any(len([a for a in dir(b) if not a.startswith("_")])
                   for b in backs):
            empties.append(f"{name}: backing exposes nothing")
    assert not empties, empties


def test_fleet_ref_paths_lazy_modules_resolve():
    from paddle_tpu.distributed.fleet.ref_paths import _LazyModule

    lazies = {name: mod for name, mod in list(sys.modules.items())
              if isinstance(mod, _LazyModule)}
    assert len(lazies) >= 10
    failures = []
    for name, mod in lazies.items():
        try:
            attrs = mod.__dict__.get("_attrs")
            if attrs is None:
                mod.__dir__()  # forces the loader
        except Exception as e:
            failures.append(f"{name}: {e}")
    assert not failures, failures
