"""fluid.backward compat (reference python/paddle/fluid/backward.py)."""
from ..static import append_backward, gradients  # noqa: F401

# reference backward.py:2204 — the 1.x spelling of gradients(); same
# signature, same grad-holder result
calc_gradient = gradients


def _append_grad_suffix_(name):
    """x → x@GRAD (reference backward.py:448)."""
    return str(name) + "@GRAD"


def _strip_grad_suffix_(name):
    """x@GRAD → x, grad/x@GRAD → x (reference backward.py:434)."""
    name = str(name)
    pos = name.find("@GRAD")
    new_name = name[:pos] if pos != -1 else name
    new_pos = new_name.rfind("grad/")
    return new_name[new_pos + 5:] if new_pos != -1 else new_name


def _as_list(x):
    """Reference backward.py helper: None → [], scalar → [scalar]."""
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]
