"""fluid.dygraph LR scheduler aliases onto optimizer.lr schedulers.

Reference: python/paddle/fluid/dygraph/learning_rate_scheduler.py. The
2.x scheduler objects already implement step()/get_lr(); fluid-era code
passes these as ``learning_rate=`` to fluid optimizers, which the compat
optimizers accept unchanged.
"""
from ...optimizer.lr import (CosineAnnealingDecay as CosineDecay,  # noqa: F401
                             ExponentialDecay, InverseTimeDecay,
                             LambdaDecay, MultiStepDecay, NaturalExpDecay,
                             NoamDecay, PiecewiseDecay, PolynomialDecay,
                             ReduceOnPlateau as ReduceLROnPlateau,
                             StepDecay)

__all__ = ['NoamDecay', 'PiecewiseDecay', 'NaturalExpDecay',
           'ExponentialDecay', 'InverseTimeDecay', 'PolynomialDecay',
           'CosineDecay', 'StepDecay', 'MultiStepDecay', 'LambdaDecay',
           'ReduceLROnPlateau']
