"""Kernel/dataloader autotune config.

Reference: python/paddle/incubate/autotune.py::set_config. On TPU the XLA
autotuner owns kernel selection (latency-hiding scheduler, fusion
autotuning), so this records the requested config and toggles what we do
control: dataloader prefetch tuning.
"""
from __future__ import annotations

import json

_config = {"kernel": {"enable": True},
           "dataloader": {"enable": True},
           "layout": {"enable": False}}


def set_config(config=None):
    """Accepts a dict or a path to a JSON file (reference semantics)."""
    global _config
    if config is None:
        for v in _config.values():
            v["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for k, v in config.items():
        _config.setdefault(k, {}).update(v)


def get_config():
    return _config
