"""QAT + PTQ (round-2 verdict #3).

Reference: nn/quant/quant_layers.py (FakeQuant observers, Quantized
layers), fluid/contrib/slim/quantization/imperative/qat.py
(ImperativeQuantAware), post_training_quantization.py.
"""
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.nn.quant import (FakeQuantChannelWiseAbsMax,
                                 FakeQuantMovingAverageAbsMax,
                                 ImperativeQuantAware, Int8Linear,
                                 PostTrainingQuantization, QuantizedConv2D,
                                 QuantizedLinear, fake_quant_dequant)


def test_qdq_values_and_ste_gradient():
    x = jnp.asarray([-3.0, -1.01, -0.5, 0.0, 0.49, 0.9, 2.5])
    scale = jnp.asarray(1.0 / 127)  # representable range [-1, 1]
    y = fake_quant_dequant(x, scale)
    # in-range values snap to the grid; out-of-range clip to the bound
    np.testing.assert_allclose(np.asarray(y)[0], -1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(y)[-1], 1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(y)[5], round(0.9 * 127) / 127,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(y)[2], round(-0.5 * 127) / 127,
                               atol=1e-6)
    g = jax.grad(lambda x: fake_quant_dequant(x, scale).sum())(x)
    # STE: unit gradient inside the representable range, zero outside
    np.testing.assert_allclose(np.asarray(g),
                               [0, 0, 1, 1, 1, 1, 0], atol=1e-6)


def test_channelwise_weight_observer():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    obs = FakeQuantChannelWiseAbsMax(quant_axis=-1)
    s = np.asarray(obs.scale_of(w))
    assert s.shape == (1, 4)
    np.testing.assert_allclose(
        s[0], np.abs(np.asarray(w)).max(axis=0) / 127, rtol=1e-6)
    err = np.abs(np.asarray(obs(paddle.to_tensor(w))._data) - np.asarray(w))
    assert err.max() <= s.max() / 2 + 1e-7


def test_moving_average_observer_updates_and_freezes():
    obs = FakeQuantMovingAverageAbsMax(momentum=0.5)
    obs.train()
    obs(paddle.to_tensor(np.asarray([127.0], np.float32)))
    s1 = float(np.asarray(obs.scale._data))
    np.testing.assert_allclose(s1, 1.0, rtol=1e-6)  # first batch: amax
    obs(paddle.to_tensor(np.asarray([0.0], np.float32)))
    s2 = float(np.asarray(obs.scale._data))
    np.testing.assert_allclose(s2, 0.5, rtol=1e-6)  # EMA
    obs.eval()
    obs(paddle.to_tensor(np.asarray([1000.0], np.float32)))
    assert float(np.asarray(obs.scale._data)) == s2  # frozen


def _lenet():
    return nn.Sequential(
        nn.Conv2D(1, 6, 5, padding=2), nn.ReLU(),
        nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(6 * 14 * 14, 32), nn.ReLU(),
        nn.Linear(32, 10))


def test_imperative_quant_aware_swaps_and_trains():
    paddle.seed(0)
    model = _lenet()
    ImperativeQuantAware().quantize(model)
    kinds = [type(l).__name__ for _, l in model.named_sublayers()]
    assert "QuantizedConv2D" in kinds and "QuantizedLinear" in kinds
    # every remaining plain Linear/Conv2D is the wrapped .inner of a
    # Quantized* layer, never a direct child of the model
    for name, l in model.named_sublayers():
        if type(l) in (nn.Linear, nn.Conv2D):
            assert name.endswith(".inner"), name

    opt = optim.Adam(learning_rate=1e-3, parameters=model.parameters())
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 1, 28, 28))
                         .astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 10, (8,)).astype(np.int64))
    model.train()
    losses = []
    for _ in range(10):
        loss = nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    assert losses[-1] < losses[0] * 0.8, losses
    # observers saw data
    for _, l in model.named_sublayers():
        if isinstance(l, (QuantizedLinear, QuantizedConv2D)):
            assert float(np.asarray(l.act_fake_quant.scale._data)) > 0


def test_qat_convert_matches_fake_quant_eval():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    ImperativeQuantAware(
        weight_quantize_type="channel_wise_abs_max").quantize(model)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((4, 16)).astype(np.float32))
    model.train()
    model(x)  # populate observers
    model.eval()
    y_qat = np.asarray(model(x)._data)
    ImperativeQuantAware.convert(model)
    kinds = [type(l).__name__ for _, l in model.named_sublayers()]
    assert "Int8Linear" in kinds and "QuantizedLinear" not in kinds
    y_int8 = np.asarray(model(x)._data)
    # same per-channel grid → near-identical outputs (activation QDQ in the
    # QAT path is the only difference, bounded by one activation LSB)
    assert np.abs(y_int8 - y_qat).max() < 0.1, np.abs(y_int8 - y_qat).max()


@pytest.mark.slow   # unblocked by the PR-12 Tensor-pytree fix; multi-
# second GSPMD compile load — slow lane per the tier-1 fast-test budget
def test_qat_llama_tiny_compiled_step():
    """QAT through the COMPILED fleet train step: observer buffers must
    thread through jit like BN stats, and training must converge."""
    import dataclasses

    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

    paddle.seed(0)
    cfg = dataclasses.replace(LLAMA_TINY, dtype="float32")
    fleet.init(is_collective=True, strategy=DistributedStrategy())
    model = LlamaForCausalLM(cfg)
    ImperativeQuantAware().quantize(model)
    model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(
        optim.AdamW(learning_rate=1e-3, parameters=model.parameters()))
    step = opt.make_train_step(model, lambda m, i, l: m(i, labels=l))
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int32))
    losses = [float(np.asarray(step(ids, ids)._data)) for _ in range(4)]
    assert losses[-1] < losses[0], losses
    scales = [float(np.asarray(l.act_fake_quant.scale._data))
              for _, l in model.named_sublayers()
              if isinstance(l, QuantizedLinear)]
    assert scales and all(s > 0 for s in scales), \
        "observer buffers did not update through the compiled step"


def test_ptq_calibrates_and_runs_through_inference():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    rng = np.random.default_rng(0)
    calib = [np.asarray(rng.standard_normal((4, 8)), np.float32)
             for _ in range(4)]
    ptq = PostTrainingQuantization(model, algo="abs_max")
    qmodel = ptq.quantize(iter(calib))
    kinds = [type(l).__name__ for _, l in qmodel.named_sublayers()]
    assert kinds.count("Int8Linear") == 2
    assert len(ptq.activation_ranges) == 2
    x = paddle.to_tensor(calib[0])
    y_q = np.asarray(qmodel(x)._data)

    # int8 weights round-trip through jit.save → inference predictor
    from paddle_tpu.static import InputSpec
    qmodel.eval()
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "ptq_model")
        paddle.jit.save(qmodel, path,
                        input_spec=[InputSpec([None, 8], "float32", "x")])
        pred = paddle.inference.create_predictor(
            paddle.inference.Config(path))
        pred.get_input_handle("x").copy_from_cpu(calib[0])
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, y_q, atol=1e-5)


def test_ptq_avg_algo_and_conv():
    paddle.seed(1)
    model = _lenet()
    rng = np.random.default_rng(1)
    calib = [np.asarray(rng.standard_normal((2, 1, 28, 28)), np.float32)
             for _ in range(3)]
    w_before = np.asarray(model[0].weight._data).copy()
    q = PostTrainingQuantization(model, algo="avg").quantize(iter(calib))
    w_after = np.asarray(q[0].weight._data)
    assert not np.array_equal(w_before, w_after)  # conv weight snapped
    # grid error bounded by half an LSB per out-channel
    s = np.abs(w_before).max(axis=(1, 2, 3), keepdims=True) / 127
    assert (np.abs(w_after - w_before) <= s / 2 + 1e-7).all()
    y = q(paddle.to_tensor(calib[0]))
    assert np.isfinite(np.asarray(y._data)).all()


def test_adaround_beats_nearest_rounding():
    """AdaRound (reference slim/quantization/adaround.py): the learned
    rounding must give LOWER layer-output reconstruction error than
    round-to-nearest on the same int8 grid, and land exactly on grid."""
    import jax.numpy as jnp
    from paddle_tpu.nn.quant import quantize_int8
    from paddle_tpu.nn.quant.adaround import adaround_weight

    rng = np.random.default_rng(0)
    w = rng.normal(size=(32, 16)).astype(np.float32)
    x = rng.normal(size=(256, 32)).astype(np.float32)
    _, s = quantize_int8(jnp.asarray(w), axis=0)
    s = np.asarray(s._data if hasattr(s, "_data") else s)

    nearest = np.clip(np.round(w / s), -127, 127) * s
    ada = np.asarray(adaround_weight(w, x, s, num_iterations=400))

    # exactly on the int8 grid (so Int8Linear.from_linear reproduces it)
    ints = ada / s
    assert np.abs(ints - np.round(ints)).max() < 1e-4
    assert np.abs(np.round(ints)).max() <= 127

    err_nearest = float(np.mean((x @ nearest - x @ w) ** 2))
    err_ada = float(np.mean((x @ ada - x @ w) ** 2))
    assert err_ada < err_nearest, (err_ada, err_nearest)
    # rounding moved at least one weight off nearest
    assert (np.round(ada / s) != np.round(w / s)).any()


def test_ptq_round_type_adaround_end_to_end():
    """PostTrainingQuantization(round_type='adaround') chains the
    learned rounding into the Int8Linear conversion."""
    from paddle_tpu.nn.quant import Int8Linear, PostTrainingQuantization

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    rng = np.random.default_rng(1)
    calib = [paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
             for _ in range(4)]
    ref_out = model(calib[0]).numpy()
    ptq = PostTrainingQuantization(model, round_type="adaround")
    q = ptq.quantize(calib, max_batches=4)
    assert any(isinstance(m, Int8Linear) for m in q.sublayers())
    out = q(calib[0]).numpy()
    # int8 model stays close to fp reference on calibration data
    assert np.isfinite(out).all()
    rel = np.abs(out - ref_out).mean() / (np.abs(ref_out).mean() + 1e-6)
    assert rel < 0.25, rel


def test_adaround_scale_pinned_through_from_linear():
    """from_linear must convert on the SAME grid the rounding was
    learned on (a recomputed abs-max scale could shift if a channel max
    rounded down) — the dequantized int8 weight reproduces the
    adarounded float weight exactly."""
    from paddle_tpu.nn.quant import Int8Linear, run_adaround

    paddle.seed(0)
    lin = nn.Linear(16, 8)
    rng = np.random.default_rng(2)
    calib = [paddle.to_tensor(rng.normal(size=(32, 16)).astype(np.float32))]
    run_adaround(calib, lin, num_iterations=200)
    assert hasattr(lin, "_adaround_scale")
    q = Int8Linear.from_linear(lin)
    np.testing.assert_allclose(np.asarray(q.scale._data).ravel(),
                               np.asarray(lin._adaround_scale).ravel())
    deq = np.asarray(q.qweight._data, np.float32) * np.asarray(q.scale._data)
    np.testing.assert_allclose(deq, np.asarray(lin.weight._data),
                               atol=1e-6)
