"""Speculative decoding for the serving engine: draft-verify with
token-identical acceptance (ROADMAP item 4(a)).

Latency-shaped traffic pays one fused target step per token; speculative
decoding (Leviathan et al., arXiv 2211.17192) spends draft flops to
collapse up to ``k`` tokens into ONE verify pass. Two draft modes:

* ``SpecConfig(draft=model)`` — a small same-family model with its own
  per-slot KV cache proposes ``k`` greedy tokens per round (k+1 fused
  draft decode steps, so the draft KV never develops holes on a full
  accept);
* ``SpecConfig(draft="ngram")`` — a draft-FREE variant in the spirit of
  lookahead/prompt-lookup decoding (Fu et al., arXiv 2402.02057): a
  host-side n-gram index over each request's prompt + emitted tokens
  proposes the continuation that followed the most recent occurrence of
  the current suffix. Zero extra XLA programs, zero extra flops when no
  n-gram matches (the slot falls back to the plain fused decode step).

**Token-identical acceptance.** Classic rejection sampling preserves
the output *distribution*; this engine makes the stronger claim — the
output *tokens* are byte-equal to the non-speculative engine, for
greedy AND sampled decoding. The verify program scores the k-token
draft chunk at k+1 positions and re-runs the request's OWN per-position
sampling: position i draws with exactly the PRNG split the
non-speculative chain would have used (``key_{t+i+1}, sk_{t+i} =
split(key_{t+i})``), and a draft token is accepted iff it EQUALS that
chain-sampled token (the token-identical specialization of rejection
sampling: acceptance probability is the indicator of the target's own
sample). The first mismatch position contributes the chain-sampled
token itself as the corrective emission, so every emitted token — and
every consumed PRNG split — is exactly what the non-speculative path
would have produced. Acceptance therefore only changes SPEED, never
tokens: adopt()/skip fast-forward, preemption replay, supervisor
rebuild and fleet migration all keep working unchanged (a speculative
engine can even adopt from a non-speculative one and vice versa).

**Paged rewind.** The verify program writes candidate K/V for all k+1
positions through the slot's block table (positions past the effective
draft width trash-redirect, the PR-8 masked-scatter machinery), then
the host rewinds the slot's ``cur`` to the accepted length. Rejected
lines sit beyond the causal bound (``view position <= cur``) and every
line is rewritten by the step that first exposes it, so rejected draft
KV is never readable; ``commit_prefix``/radix only ever index prompt
blocks, so unverified tokens can never be published for sharing.
"""
from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["SpecConfig"]

#: EngineMetrics counters the supervisor accumulates across rebuilds
#: (``EngineSupervisor.spec_totals``) so acceptance history survives an
#: engine incarnation being condemned.
SPEC_COUNTER_KEYS = ("spec_steps", "draft_steps", "spec_proposed_tokens",
                     "spec_accepted_tokens", "spec_emitted_tokens")


@dataclasses.dataclass
class SpecConfig:
    """Speculative-decoding configuration for ``Engine(speculative=...)``.

    ``draft`` is ``"ngram"`` (host-side n-gram lookahead over
    prompt+emitted tokens), a same-family CausalLM (model-draft), or any
    object with ``propose(ctx_ids, k) -> int32[<=k] | None`` (a custom
    host-side proposer — the chaos/worst-case test hook). ``k`` is the
    draft width: one verify pass scores k proposed tokens at k+1
    positions and emits between 1 and k+1 tokens. ``ngram_min`` /
    ``ngram_max`` bound the suffix order the n-gram proposer matches
    (longest first)."""

    draft: object = "ngram"
    k: int = 4
    ngram_max: int = 3
    ngram_min: int = 2

    def __post_init__(self):
        if int(self.k) < 1:
            raise ValueError("SpecConfig.k must be >= 1")
        self.k = int(self.k)
        if self.draft == "ngram":
            if not (1 <= int(self.ngram_min) <= int(self.ngram_max)):
                raise ValueError(
                    "need 1 <= ngram_min <= ngram_max")
            self.ngram_min = int(self.ngram_min)
            self.ngram_max = int(self.ngram_max)

    def draft_kind(self):
        if self.draft == "ngram":
            return "ngram"
        if hasattr(self.draft, "propose"):
            return "custom"
        return "model"


class _NgramState:
    """Per-handle incremental n-gram index: for each order n, the
    position AFTER the most recent occurrence of every n-gram ending at
    an already-continued position. Append-only (a request's context only
    grows, and replay/adopt rebuilds the same prefix), so indexing work
    is O(new tokens x orders) per proposal."""

    __slots__ = ("idx", "upto")

    def __init__(self, nmin, nmax):
        self.idx = {n: {} for n in range(nmin, nmax + 1)}
        self.upto = 0     # n-grams ending before this position are indexed


class NgramProposer:
    """Draft-free lookahead: propose the tokens that followed the most
    recent earlier occurrence of the context's current suffix (longest
    matching order first). Entirely host-side — no draft model, no extra
    XLA programs; a slot with no match falls back to the plain fused
    decode step for that iteration."""

    def __init__(self, cfg: SpecConfig):
        self.nmin = cfg.ngram_min
        self.nmax = cfg.ngram_max

    def propose(self, h, k_cap):
        ctx = h.prompt_ids.tolist() + h.tokens
        L = len(ctx)
        st = getattr(h, "_spec_ngram", None)
        if st is None:
            st = h._spec_ngram = _NgramState(self.nmin, self.nmax)
        # index n-grams ending at positions [upto, L-2]: each has a
        # known continuation at the next position
        for e in range(st.upto, L - 1):
            for n in range(self.nmin, self.nmax + 1):
                if e - n + 1 < 0:
                    continue
                st.idx[n][tuple(ctx[e - n + 1:e + 1])] = e + 1
        st.upto = max(st.upto, L - 1)
        for n in range(min(self.nmax, L - 1), self.nmin - 1, -1):
            pos = st.idx[n].get(tuple(ctx[L - n:]))
            if pos is not None:
                out = ctx[pos:pos + k_cap]
                if out:
                    return np.asarray(out, np.int32)
        return None


class _ModelDraft:
    """Same-family small-model draft with its own slot-layout KV cache
    (one [layers, n_slots, max_len, kv, hd] slab pair, tracking the
    target engine's slots one-for-one — no separate allocator). The
    draft runs GREEDY: acceptance compares proposals against the
    target's chain-sampled tokens, so draft sampling would only add
    noise. Draft programs reuse the engine's module-level slot-layout
    prefill/decode jits (with the draft's own weight shapes — they count
    toward the compile budget as ``draft_buckets_seen`` + one draft
    decode program)."""

    def __init__(self, engine, model):
        from .engine import _make_arch
        w, hp, geo = _make_arch(model)
        if hp["arch"] != engine._hp["arch"]:
            raise ValueError(
                f"draft model arch {hp['arch']!r} != target arch "
                f"{engine._hp['arch']!r}: speculative drafts must be "
                "same-family")
        if int(w["head"].shape[-1]) != engine._vocab:
            raise ValueError(
                f"draft vocab {int(w['head'].shape[-1])} != target "
                f"vocab {engine._vocab}")
        if engine.max_len > geo["max_pos"] and hp["arch"] == "gpt":
            raise ValueError("draft position table < engine max_len")
        self.engine = engine
        self._w = w
        # greedy statics: the draft's sampled path is never used
        self._statics = dict(hp, do_sample=False, top_k=0, top_p=None)
        S, T = engine.n_slots, engine.max_len
        shape = (geo["n_layers"], S, T, geo["kv_heads"], geo["head_dim"])
        self.kc = np.zeros(shape, geo["dtype"])
        self.vc = np.zeros(shape, geo["dtype"])
        self.tok = np.zeros(S, np.int32)
        self.cur = np.zeros(S, np.int32)
        self.keys = np.zeros((S, 2), np.uint32)
        self.temps = np.ones(S, np.float32)

    @staticmethod
    def _host(a):
        a = np.asarray(a)
        return a if a.flags.writeable else a.copy()

    def _programs(self):
        from . import engine as E
        if self.engine._donate:
            return E._PREFILL_DONATED, E._DECODE_DONATED
        return E._PREFILL, E._DECODE

    def on_admit(self, h, full):
        """Prefill the draft's KV for the slot's full token history
        (prompt + replayed tokens) — the admission/replay counterpart of
        the target prefill. The draft then chains from the TARGET's
        sampled token, not its own first guess."""
        from ..observability import tracing as _tracing
        from ..observability.compile_attr import compile_scope
        eng = self.engine
        slot, n_eff = h.slot, len(full)
        Lb = eng._bucket(n_eff)
        eng.draft_buckets_seen.add(Lb)
        ids = np.zeros((1, Lb), np.int32)
        ids[0, :n_eff] = full
        prefill, _ = self._programs()
        with _tracing.span("spec.draft_prefill", cat="serving",
                           trace_id=h.trace_id, request_id=h.request_id,
                           bucket=Lb), compile_scope(f"spec.draft:L{Lb}"):
            out = eng._run_program(
                "draft_prefill", ("draft_prefill", Lb), prefill,
                (self._w, self.kc, self.vc, self.tok, self.cur,
                 self.keys, ids, np.int32(n_eff), np.int32(slot),
                 np.uint32(0), np.int32(0), np.float32(1.0),
                 eng._vmask[slot].copy()),
                self._statics, f"spec.draft:L{Lb}")
        self.kc, self.vc, tok, self.cur, self.keys, _ = out
        tok = self._host(tok)
        tok[slot] = h.tokens[-1]
        self.tok = tok

    def propose_all(self, cand):
        """k+1 fused greedy draft decode steps over every
        verify-eligible slot at once; the first k outputs are the
        proposals (the extra step writes the k-th proposal's KV so a
        full accept leaves no draft-cache hole)."""
        from ..observability import tracing as _tracing
        from ..observability.compile_attr import compile_scope
        eng = self.engine
        if not cand:
            return {}
        active = np.zeros(eng.n_slots, bool)
        for h, _ in cand:
            active[h.slot] = True
        _, decode = self._programs()
        outs = {h.slot: [] for h, _ in cand}
        k = eng.spec.k
        with _tracing.span("spec.draft", cat="serving",
                           n_slots=len(cand), k=k), \
                compile_scope("spec.draft"):
            for _ in range(k + 1):
                out = eng._run_program(
                    "draft_decode", ("draft_decode",), decode,
                    (self._w, self.kc, self.vc, self.tok, self.cur,
                     active, self.keys, self.temps, eng._vmask.copy()),
                    self._statics, "spec.draft")
                nxt, self.kc, self.vc, self.cur, self.keys = out
                self.tok = nxt
                toks = np.asarray(nxt)
                for h, _ in cand:
                    outs[h.slot].append(int(toks[h.slot]))
                eng.metrics.draft_steps += 1
        eng.draft_decode_used = True
        return {slot: np.asarray(v[:k], np.int32)
                for slot, v in outs.items()}

    def after_verify(self, h, last_tok, new_cur):
        """Rewind/advance the draft to the target's post-verify state:
        tok = the last emitted (chain-sampled) token, cur = the accepted
        length. Draft lines beyond sit past the causal bound and are
        rewritten before they are ever attendable — the same stale-line
        argument as slot reuse."""
        slot = h.slot
        tok = self._host(self.tok)
        cur = self._host(self.cur)
        tok[slot] = last_tok
        cur[slot] = new_cur
        self.tok, self.cur = tok, cur

    def probe_specs(self, buckets):
        """(kind, hkey, jitted, abstract args, statics, origin) probes
        for the draft program set — precompile_aot coverage mirroring
        the live draft call sites operand for operand."""
        import jax
        eng = self.engine

        def sds(a):
            return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

        w = {k: sds(v) for k, v in self._w.items()}
        kc, vc = sds(self.kc), sds(self.vc)
        S = eng.n_slots
        tok = jax.ShapeDtypeStruct((S,), np.int32)
        cur = jax.ShapeDtypeStruct((S,), np.int32)
        keys = jax.ShapeDtypeStruct((S, 2), np.uint32)
        temps = jax.ShapeDtypeStruct((S,), np.float32)
        act = jax.ShapeDtypeStruct((S,), np.bool_)
        vm = jax.ShapeDtypeStruct((S, eng._vocab), np.float32)
        i32 = jax.ShapeDtypeStruct((), np.int32)
        u32 = jax.ShapeDtypeStruct((), np.uint32)
        f32 = jax.ShapeDtypeStruct((), np.float32)
        vrow = jax.ShapeDtypeStruct((eng._vocab,), np.float32)
        prefill, decode = self._programs()
        specs = []
        for Lb in buckets:
            ids = jax.ShapeDtypeStruct((1, int(Lb)), np.int32)
            specs.append((
                "draft_prefill", ("draft_prefill", int(Lb)), prefill,
                (w, kc, vc, tok, cur, keys, ids, i32, i32, u32, i32, f32,
                 vrow),
                self._statics, f"spec.draft:L{Lb}"))
        specs.append((
            "draft_decode", ("draft_decode",), decode,
            (w, kc, vc, tok, cur, act, keys, temps, vm),
            self._statics, "spec.draft"))
        return specs


class _HostProposerAdapter:
    """Wrap a custom ``propose(ctx_ids, k) -> tokens|None`` object (or
    the built-in NgramProposer, which takes the handle directly)."""

    def __init__(self, proposer, by_handle):
        self.proposer = proposer
        self.by_handle = by_handle

    def on_admit(self, h, full):
        pass

    def after_verify(self, h, last_tok, new_cur):
        pass

    def propose_all(self, cand):
        out = {}
        for h, k_cap in cand:
            if self.by_handle:
                p = self.proposer.propose(h, k_cap)
            else:
                ctx = np.concatenate(
                    [h.prompt_ids, np.asarray(h.tokens, np.int32)])
                p = self.proposer.propose(ctx, k_cap)
            if p is not None and len(p):
                out[h.slot] = np.asarray(p[:k_cap], np.int32)
        return out

    def probe_specs(self, buckets):
        return []


def make_runtime(engine, cfg: SpecConfig, model=None):
    """Build the draft runtime for an engine: NgramProposer ("ngram"),
    a custom host proposer (``propose`` protocol), or a model draft."""
    kind = cfg.draft_kind()
    if kind == "ngram":
        return _HostProposerAdapter(NgramProposer(cfg), by_handle=True)
    if kind == "custom":
        return _HostProposerAdapter(cfg.draft, by_handle=False)
    return _ModelDraft(engine, cfg.draft)
