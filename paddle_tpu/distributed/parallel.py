"""DataParallel wrapper + env helpers (reference:
python/paddle/distributed/parallel.py).

Under the single-controller XLA model, DataParallel does not need grad
hooks: a pjit step with batch sharded over "dp" psums grads automatically.
This wrapper keeps the reference API for eager scripts and marks the model
so hapi/fleet builders shard the batch.
"""
from __future__ import annotations

from ..nn.layer_base import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass


class ParallelEnv:
    def __init__(self):
        from .collective import get_rank, get_world_size
        self.rank = get_rank()
        self.world_size = get_world_size()
        self.device_id = 0

    @property
    def local_rank(self):
        return self.rank

    @property
    def nranks(self):
        return self.world_size
