"""Dynamic-to-static control-flow conversion.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:999 + convert_operators.py — the reference rewrites a
function's AST so python ``if``/``while`` over tensors become graph ops
(convert_ifelse/convert_while_loop). The TPU-native analog rewrites them to
``lax.cond``/``lax.while_loop`` calls; when the predicate is a concrete
(non-traced) value the original python control flow runs unchanged, so the
same converted function works eagerly and under jit.

Conversion contract (the "common cases" shim):
* ``if``/``elif``/``else`` and ``while`` statements are converted when their
  bodies contain no ``return``/``break``/``continue``/``yield`` — those fall
  back to python control flow (fine eagerly; under jit a tensor predicate
  will raise jax's concretization error, pointing here).
* names assigned inside a branch/loop body are threaded through the
  lax primitive as carried state; reads of enclosing locals happen via
  closure. Both branches of a converted ``if`` must produce matching
  shapes/dtypes for threaded names (lax.cond's contract).
* conversion is source-based (inspect.getsource); functions without
  retrievable source (REPL lambdas, C extensions) run unconverted.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable

import jax
import jax.numpy as jnp


class _Undefined:
    """Sentinel for a name not yet bound when control flow is converted."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"


UNDEFINED = _Undefined()


def pack_args(*thunks):
    """Evaluate name thunks, mapping unbound locals to UNDEFINED."""
    vals = []
    for t in thunks:
        try:
            vals.append(t())
        except NameError:
            vals.append(UNDEFINED)
    return tuple(vals)


def _raw(x):
    from ..tensor import Tensor

    return x._data if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(_raw(x), jax.core.Tracer)


def _to_carry(vals):
    """Tensors -> raw arrays; python scalars -> arrays (stable carry
    dtypes); returns (raw_leaves, rewrap) where rewrap restores Tensors."""
    from ..tensor import Tensor

    is_tensor = [isinstance(v, Tensor) for v in vals]
    raws = []
    for v in vals:
        r = _raw(v)
        if isinstance(r, _Undefined):
            r = jnp.int32(0)  # dummy; branches must assign before use
        elif isinstance(r, (bool, int, float)):
            r = jnp.asarray(r)
        raws.append(r)

    def rewrap(raws_out):
        return tuple(
            Tensor(r, stop_gradient=False) if t else r
            for r, t in zip(raws_out, is_tensor))

    return tuple(raws), rewrap


def convert_ifelse(pred, true_fn, false_fn, vals):
    """``if pred: ... else: ...`` with assigned names threaded via vals."""
    from ..tensor import Tensor

    p = _raw(pred)
    if not isinstance(p, jax.core.Tracer):
        return true_fn(*vals) if bool(p) else false_fn(*vals)

    raws, rewrap = _to_carry(vals)
    out_kinds = []  # is-Tensor per output, recorded while tracing branches

    def _branch(fn):
        def run(raw_ops):
            outs = fn(*rewrap(raw_ops))
            if not isinstance(outs, tuple):
                outs = (outs,)
            out_kinds[:] = [isinstance(o, Tensor) for o in outs]
            return tuple(jnp.asarray(_raw(o)) for o in outs)
        return run

    out = jax.lax.cond(jnp.asarray(p, bool), _branch(true_fn),
                       _branch(false_fn), raws)
    return tuple(Tensor(o, stop_gradient=False) if t else o
                 for o, t in zip(out, out_kinds))


def convert_while(cond_fn, body_fn, vals):
    """``while cond: body`` with assigned names threaded via vals."""
    probe = cond_fn(*vals)
    traced = _is_traced(probe) or any(_is_traced(v) for v in vals)
    if not traced:
        while bool(_raw(cond_fn(*vals))):
            new = body_fn(*vals)
            vals = new if isinstance(new, tuple) else (new,)
        return vals

    from ..tensor import Tensor

    raws, rewrap = _to_carry(vals)
    undef = [isinstance(_raw(v), _Undefined) for v in vals]
    out_kinds = []

    def cond(raw_ops):
        return jnp.asarray(_raw(cond_fn(*rewrap(raw_ops))), bool)

    def body(raw_ops):
        outs = body_fn(*rewrap(raw_ops))
        if not isinstance(outs, tuple):
            outs = (outs,)
        out_kinds[:] = [isinstance(o, Tensor) for o in outs]
        return tuple(jnp.asarray(_raw(o)) for o in outs)

    # Settle the carry structure: names first assigned inside the loop enter
    # as dummies, and weak-typed scalars can promote — run the body
    # abstractly (eval_shape) and align the init carry to its output avals
    # (two rounds reach the fixed point for dtype promotion chains).
    for _ in range(2):
        out_avals = jax.eval_shape(body, raws)
        aligned = []
        for r, a, u in zip(raws, out_avals, undef):
            r = jnp.asarray(r)
            if u and (tuple(r.shape) != tuple(a.shape) or r.dtype != a.dtype):
                aligned.append(jnp.zeros(a.shape, a.dtype))
            elif r.dtype != a.dtype and tuple(r.shape) == tuple(a.shape):
                aligned.append(r.astype(a.dtype))
            else:
                aligned.append(r)
        raws = tuple(aligned)

    out = jax.lax.while_loop(cond, body, raws)
    if len(out_kinds) == len(out):
        return tuple(Tensor(o, stop_gradient=False) if t else o
                     for o, t in zip(out, out_kinds))
    return rewrap(out)


def convert_bool(x):
    """Predicate coercion used by converted ``if`` tests (keeps Tensors /
    tracers as-is; convert_ifelse decides the path)."""
    return x


def loop_cond(i, stop, step):
    """`for i in range(start, stop, step)` desugars to a while with this
    condition; handles tensor bounds (negative tensor steps assume the
    caller's python semantics — positive — like the reference's
    convert_range)."""
    if isinstance(step, (int, float)) and step < 0:
        return i > stop
    return i < stop


# ---------------------------------------------------------------------------
# AST transformation
# ---------------------------------------------------------------------------

_JST = "_pt_jst"  # module alias injected into the function's globals


class _AssignCollector(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)  # def binds the name; don't descend

    def visit_AsyncFunctionDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned(stmts) -> set:
    c = _AssignCollector()
    for s in stmts:
        c.visit(s)
    return c.names


class _Disallowed(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_Yield(self, node):
        self.found = True

    def visit_YieldFrom(self, node):
        self.found = True

    def visit_FunctionDef(self, node):
        pass  # nested defs own their returns

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


def _has_disallowed(stmts) -> bool:
    d = _Disallowed()
    for s in stmts:
        d.visit(s)
    return d.found


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _tuple_of(names, ctx=None):
    return ast.Tuple(elts=[_name(n, ctx or ast.Load()) for n in names],
                     ctx=ctx or ast.Load())


def _pack_call(names):
    # _pt_jst.pack_args((lambda: a), (lambda: b), ...)
    lams = [ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                           kw_defaults=[], defaults=[]),
        body=_name(n)) for n in names]
    return ast.Call(
        func=ast.Attribute(value=_name(_JST), attr="pack_args",
                           ctx=ast.Load()),
        args=lams, keywords=[])


def _fn_def(fname, argnames, body_stmts, ret_names):
    body = list(body_stmts)
    body.append(ast.Return(value=_tuple_of(ret_names)))
    return ast.FunctionDef(
        name=fname,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=a) for a in argnames],
            kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=body, decorator_list=[], returns=None)


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.n = 0

    def _next(self):
        self.n += 1
        return self.n

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_disallowed(node.body) or _has_disallowed(node.orelse):
            return node
        names = sorted(_assigned(node.body) | _assigned(node.orelse))
        i = self._next()
        tname, fname = f"__pt_true_{i}", f"__pt_false_{i}"
        true_def = _fn_def(tname, names, node.body, names)
        false_def = _fn_def(fname, names, node.orelse or [ast.Pass()], names)
        call = ast.Call(
            func=ast.Attribute(value=_name(_JST), attr="convert_ifelse",
                               ctx=ast.Load()),
            args=[node.test, _name(tname), _name(fname), _pack_call(names)],
            keywords=[])
        if names:
            assign = ast.Assign(targets=[_tuple_of(names, ast.Store())],
                                value=call)
        else:
            assign = ast.Expr(value=call)
        return [true_def, false_def, assign]

    def visit_For(self, node):
        """``for i in range(...)`` → init + while (then converted like any
        while). Other iterables stay python (reference converts range and
        enumerate; range covers the tensor-bound cases)."""
        self.generic_visit(node)
        if (_has_disallowed(node.body) or node.orelse
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords):
            return node
        args = node.iter.args
        if len(args) == 1:
            start, stop, step = ast.Constant(0), args[0], ast.Constant(1)
        elif len(args) == 2:
            start, stop, step = args[0], args[1], ast.Constant(1)
        elif len(args) == 3:
            start, stop, step = args
        else:
            return node
        i = self._next()
        ev, tv = f"__pt_rstop_{i}", f"__pt_rstep_{i}"
        tgt = node.target.id
        inits = [
            ast.Assign(targets=[_name(ev, ast.Store())], value=stop),
            ast.Assign(targets=[_name(tv, ast.Store())], value=step),
            ast.Assign(targets=[_name(tgt, ast.Store())], value=start),
        ]
        bump = ast.Assign(
            targets=[_name(tgt, ast.Store())],
            value=ast.BinOp(left=_name(tgt), op=ast.Add(), right=_name(tv)))
        test = ast.Call(
            func=ast.Attribute(value=_name(_JST), attr="loop_cond",
                               ctx=ast.Load()),
            args=[_name(tgt), _name(ev), _name(tv)], keywords=[])
        wh = ast.While(test=test, body=list(node.body) + [bump], orelse=[])
        out = self.visit_While(wh)
        return inits + (out if isinstance(out, list) else [out])

    def visit_While(self, node):
        self.generic_visit(node)
        if (_has_disallowed(node.body) or node.orelse):
            return node
        names = sorted(_assigned(node.body))
        if not names:
            return node
        i = self._next()
        cname, bname = f"__pt_cond_{i}", f"__pt_body_{i}"
        cond_def = ast.FunctionDef(
            name=cname,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=a) for a in names],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[ast.Return(value=node.test)],
            decorator_list=[], returns=None)
        body_def = _fn_def(bname, names, node.body, names)
        call = ast.Call(
            func=ast.Attribute(value=_name(_JST), attr="convert_while",
                               ctx=ast.Load()),
            args=[_name(cname), _name(bname), _pack_call(names)],
            keywords=[])
        assign = ast.Assign(targets=[_tuple_of(names, ast.Store())],
                            value=call)
        return [cond_def, body_def, assign]


def convert_control_flow(fn: Callable) -> Callable:
    """Return fn with tensor control flow converted; fn itself on failure."""
    inner = fn.__func__ if inspect.ismethod(fn) else fn
    if not inspect.isfunction(inner):
        return fn
    if inner.__code__.co_freevars:
        # Closure cells can only be materialized by VALUE into the exec'd
        # copy — a later rebinding of the closed-over variable (or zero-arg
        # super()'s __class__ cell) would silently diverge from the
        # original function. Skip conversion; tensor control flow inside
        # closures falls back to static.nn.cond/while_loop.
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(inner))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    for dec in fdef.decorator_list:
        # only the to_static decorator itself may be stripped; any other
        # decorator would be silently dropped by re-exec — skip conversion
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = d.attr if isinstance(d, ast.Attribute) else getattr(d, "id",
                                                                   "")
        if name not in ("to_static", "not_to_static"):
            return fn
    fdef.decorator_list = []
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)

    import paddle_tpu.jit.dy2static as _self

    glb = dict(inner.__globals__)
    glb[_JST] = _self
    try:
        code = compile(new_tree, filename=f"<dy2static {inner.__name__}>",
                       mode="exec")
        exec(code, glb)
        converted = glb[fdef.name]
    except Exception:
        return fn
    functools.update_wrapper(converted, inner, updated=())
    converted.__wrapped_original__ = inner
    if inspect.ismethod(fn):
        return converted.__get__(fn.__self__, type(fn.__self__))
    return converted
