"""Resilience regression for the comm-opt train step (PR 12 satellite):
error-feedback residuals and ZeRO-1-sharded moments are explicit
functional state, so they must round-trip through the PR-6
CheckpointManager (COMMIT/CRC) bitwise, and a re-meshed 8 -> 4 restore
must re-shard the flat owner-sharded state positionally."""
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.resilience import TrainState


def _build(dp, grad_compress="int8", zero1=True, seed=0):
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    strategy.comm_opt = True
    strategy.comm_opt_configs = {"grad_compress": grad_compress,
                                 "zero1": zero1, "qblock": 64}
    fleet.init(is_collective=True, strategy=strategy)
    paddle_tpu.seed(seed)
    model = fleet.distributed_model(
        nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 1)))
    opt = fleet.distributed_optimizer(
        optim.Adam(learning_rate=0.01, parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(
        model, lambda m, x, y: ((m(x) - y) ** 2).mean())
    return step, model


def _data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    w = rng.standard_normal((8,)).astype(np.float32)
    y = (x @ w)[:, None].astype(np.float32)
    return paddle_tpu.to_tensor(x), paddle_tpu.to_tensor(y)


def _owner_flat(leaf, n):
    """[dp, tp, chunk] owner-sharded flat state -> logical [n] vector."""
    a = np.asarray(leaf)
    return a.transpose(1, 0, 2).reshape(-1)[:n]


def test_kill_and_resume_bitwise(tmp_path):
    """SIGKILL-equivalent: losses after restore are byte-equal to the
    uninterrupted run — error-feedback residuals and sharded moments
    included in the snapshot make that possible."""
    xt, yt = _data()
    step, _ = _build(dp=4)
    for _ in range(3):
        step(xt, yt)
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    state = TrainState(train_step=step)
    mgr.save(3, state.capture(), async_save=False)
    cont = [float(np.asarray(step(xt, yt)._data)) for _ in range(3)]

    # "fresh process": new model/step from a different seed, restore
    step2, _ = _build(dp=4, seed=123)
    state2 = TrainState(train_step=step2)
    _, snap = mgr.restore_latest(template=state2.capture())
    state2.restore(snap)
    resumed = [float(np.asarray(step2(xt, yt)._data)) for _ in range(3)]
    assert resumed == cont


def test_remesh_8_to_4_reshards_flat_state(tmp_path):
    """dp=8 -> dp=4 restore: the owner-sharded flat moments and the e2
    residual land positionally (logical vector preserved), e1's total
    dropped-error mass is conserved, and training continues finite."""
    xt, yt = _data()
    step8, _ = _build(dp=8)
    for _ in range(4):
        step8(xt, yt)
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    mgr.save(4, TrainState(train_step=step8).capture(), async_save=False)
    n = step8.n_local
    m1_8 = _owner_flat(step8._opt_state["moment1"], n)
    e1_8_total = np.asarray(step8._ef["e1"]).sum(axis=(0, 1))[:n]
    e2_8 = _owner_flat(step8._ef["e2"], n) if "e2" in step8._ef else None

    step4, _ = _build(dp=4, seed=7)
    state4 = TrainState(train_step=step4)
    # template-free restore: the snapshot is dp=8-shaped while this
    # step is dp=4 — load raw arrays and let load_state_dict re-shard
    _, snap = mgr.restore_latest(template=None)
    state4.restore(snap)
    assert step4.n_local == n
    m1_4 = _owner_flat(step4._opt_state["moment1"], n)
    np.testing.assert_array_equal(m1_4, m1_8)
    if e2_8 is not None:
        e2_4 = _owner_flat(step4._ef["e2"], n)
        np.testing.assert_array_equal(e2_4, e2_8)
    # e1 is per-replica: the re-mesh conserves the summed residual
    e1_4_total = np.asarray(step4._ef["e1"]).sum(axis=(0, 1))[:n]
    np.testing.assert_allclose(e1_4_total, e1_8_total, rtol=1e-6)
    # and the re-meshed step trains on, finite
    losses = [float(np.asarray(step4(xt, yt)._data)) for _ in range(3)]
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] <= losses[0] * 1.5


def test_state_dict_roundtrip_without_manager():
    """Plain state_dict/load_state_dict (same mesh) is bitwise."""
    xt, yt = _data()
    step, _ = _build(dp=4)
    for _ in range(2):
        step(xt, yt)
    snap = step.state_dict()
    cont = float(np.asarray(step(xt, yt)._data))
    step2, _ = _build(dp=4, seed=9)
    step2.load_state_dict(snap)
    resumed = float(np.asarray(step2(xt, yt)._data))
    assert resumed == cont
