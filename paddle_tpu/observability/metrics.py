"""Typed metrics registry: Counter / Gauge / Histogram with labels.

The framework's telemetry was four disconnected counter snapshots
(``profiler.dispatch_counters()`` and friends) plus per-tool JSON
ledgers. This module is the one substrate they all surface through: a
process-wide :class:`MetricsRegistry` of typed instruments, exportable
as a JSON snapshot (``snapshot()``) or Prometheus text exposition
(``to_prometheus()``), with the existing counter sources attached as
pull-time *collectors* (see ``collectors.py``) so their hot paths keep
their plain-attribute increments and pay nothing at record time.

Overhead policy: instruments are mutated only where something already
slow happens (a compile, a decode step, a checkpoint save); scrapes do
the aggregation work. An idle registry costs a dict and some ints.

``Histogram`` supports a count-windowed rolling view for live quantile
queries: ``window=N`` keeps two generations of bucket counts rotated
every ``N // 2`` observations, so ``percentile(p)`` reflects roughly
the last N observations (the serving ITL p50/p95 behind brownout
shedding and ``EngineOverloaded.retry_after_s``) while the exported
cumulative buckets never lose history.
"""
from __future__ import annotations

import bisect
import json
import re
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "DEFAULT_LATENCY_BUCKETS", "counter", "gauge", "histogram",
    "register_collector", "snapshot", "to_prometheus",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Exponential latency bounds (seconds), 100us .. 10s — wide enough for
#: a CPU decode step and a TPU one.
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _check_name(name):
    if not _NAME_RE.match(name or ""):
        raise ValueError(f"invalid metric name {name!r}")
    return name


class _Metric:
    """Shared instrument plumbing: name/help/labels + child table."""

    kind = None

    def __init__(self, name, help="", labelnames=(), registry="default"):
        self.name = _check_name(name)
        self.help = str(help)
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self._lock = threading.Lock()
        self._children = {}     # label-value tuple -> child state
        if registry == "default":
            registry = REGISTRY
        if registry is not None:
            registry.register(self)

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(kv)}")
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
        return child

    def _unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; call "
                ".labels(...) first")
        return self.labels()

    def samples(self):
        """[(labels_dict, child_state)] snapshot for export."""
        with self._lock:
            return [(dict(zip(self.labelnames, key)), child)
                    for key, child in sorted(self._children.items())]


class _CounterChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n=1.0):
        if n < 0:
            raise ValueError("counters only go up")
        self.value += n


class Counter(_Metric):
    """Monotonically increasing value (events, seconds-of-work)."""

    kind = "counter"
    _new_child = _CounterChild

    def inc(self, n=1.0):
        self._unlabeled().inc(n)

    @property
    def value(self):
        with self._lock:
            return sum(c.value for c in self._children.values())


class _GaugeChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = float(v)

    def inc(self, n=1.0):
        self.value += n

    def dec(self, n=1.0):
        self.value -= n


class Gauge(_Metric):
    """Point-in-time value (queue depth, pool occupancy)."""

    kind = "gauge"
    _new_child = _GaugeChild

    def set(self, v):
        self._unlabeled().set(v)

    def inc(self, n=1.0):
        self._unlabeled().inc(n)

    def dec(self, n=1.0):
        self._unlabeled().dec(n)

    @property
    def value(self):
        with self._lock:
            return sum(c.value for c in self._children.values())


class Histogram:
    """Bucketed distribution with cumulative export and an optional
    count-windowed rolling view for quantiles.

    Unlabeled (label a histogram by creating one per stream and merging
    at collect time — see the serving ITL collector). ``percentile(p)``
    interpolates linearly inside the bucket that holds the rank; with
    ``window=N`` it covers the last ~N observations (two generations
    rotated every ``N // 2``), otherwise the full history.
    """

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_LATENCY_BUCKETS,
                 window=None, registry="default"):
        self.name = _check_name(name)
        self.help = str(help)
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("need at least one bucket bound")
        self._lock = threading.Lock()
        n = len(self.bounds) + 1          # last slot: +Inf
        self._counts = [0] * n            # cumulative-forever, for export
        self.sum = 0.0
        self.count = 0
        self.window = None if window is None else max(2, int(window))
        if self.window:
            self._hot = [0] * n
            self._cold = [0] * n
            self._hot_n = 0
        if registry == "default":
            registry = REGISTRY
        if registry is not None:
            registry.register(self)

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self.sum += v
            self.count += 1
            if self.window:
                if self._hot_n >= self.window // 2:
                    self._cold = self._hot
                    self._hot = [0] * len(self._counts)
                    self._hot_n = 0
                self._hot[i] += 1
                self._hot_n += 1

    def _view(self):
        if not self.window:
            return self._counts
        return [h + c for h, c in zip(self._hot, self._cold)]

    def percentile(self, p):
        """Approximate percentile (linear interpolation inside the
        owning bucket) over the rolling window when one is configured,
        else over all observations. None before the first observe."""
        with self._lock:
            counts = list(self._view())
        n = sum(counts)
        if n == 0:
            return None
        target = max(1, min(n, p / 100.0 * n))
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = self.bounds[min(i, len(self.bounds) - 1)]
                frac = (target - cum) / c
                return lo + frac * (hi - lo)
            cum += c
        return self.bounds[-1]

    def cumulative(self):
        """[(upper_bound, cumulative_count)] + (+Inf, total) for the
        Prometheus exposition (never windowed)."""
        with self._lock:
            out, cum = [], 0
            for b, c in zip(self.bounds, self._counts):
                cum += c
                out.append((b, cum))
            out.append((float("inf"), cum + self._counts[-1]))
            return out

    def merge_counts(self, into):
        """Add this histogram's cumulative per-bucket counts into the
        list ``into`` (same bucket bounds assumed) — collector-side
        aggregation across streams."""
        with self._lock:
            for i, c in enumerate(self._counts):
                into[i] += c
            return self.sum, self.count


class MetricsRegistry:
    """Named instruments + pull-time collectors, one scrape surface."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._collectors = []     # (name, fn) -> iterable of families

    def register(self, metric):
        with self._lock:
            cur = self._metrics.get(metric.name)
            if cur is not None and cur is not metric:
                raise ValueError(
                    f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric
        return metric

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def collector(self, fn, name=None):
        """Register a pull-time source: ``fn()`` returns an iterable of
        family dicts (``{"name", "kind", "help", "samples": [(labels,
        value)]}`` or histogram families with ``"buckets"/"sum"/
        "count"``). Re-registering under the same name replaces."""
        name = name or getattr(fn, "__name__", repr(fn))
        with self._lock:
            self._collectors = [(n, f) for n, f in self._collectors
                                if n != name]
            self._collectors.append((name, fn))
        return fn

    # -- scrape ------------------------------------------------------------

    def collect(self):
        """Yield family dicts from every instrument and collector.
        Collector exceptions are captured into a
        ``paddle_collector_errors`` family instead of killing the
        scrape."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        for m in metrics:
            if m.kind == "histogram":
                yield {"name": m.name, "kind": "histogram",
                       "help": m.help, "buckets": m.cumulative(),
                       "sum": m.sum, "count": m.count}
            else:
                yield {"name": m.name, "kind": m.kind, "help": m.help,
                       "samples": [(lbl, child.value)
                                   for lbl, child in m.samples()]}
        errors = []
        for name, fn in collectors:
            try:
                for fam in fn():
                    yield fam
            except Exception as e:
                errors.append((name, f"{type(e).__name__}: {e}"))
        if errors:
            yield {"name": "paddle_collector_errors", "kind": "gauge",
                   "help": "collectors that failed this scrape",
                   "samples": [({"collector": n, "error": msg}, 1.0)
                               for n, msg in errors]}

    def snapshot(self):
        """JSON-serializable snapshot of every family."""
        out = {}
        for fam in self.collect():
            if fam["kind"] == "histogram":
                out[fam["name"]] = {
                    "kind": "histogram", "sum": fam["sum"],
                    "count": fam["count"],
                    "buckets": [[("+Inf" if b == float("inf") else b), c]
                                for b, c in fam["buckets"]]}
            else:
                out[fam["name"]] = {
                    "kind": fam["kind"],
                    "samples": [{"labels": lbl, "value": v}
                                for lbl, v in fam["samples"]]}
        json.dumps(out)       # a non-serializable family is a bug HERE
        return out

    def to_prometheus(self):
        """Prometheus text exposition format 0.0.4."""
        lines = []
        for fam in self.collect():
            name = fam["name"]
            if fam.get("help"):
                lines.append(f"# HELP {name} {_esc_help(fam['help'])}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            if fam["kind"] == "histogram":
                for b, c in fam["buckets"]:
                    le = "+Inf" if b == float("inf") else _fmt_num(b)
                    lines.append(
                        f'{name}_bucket{{le="{le}"}} {int(c)}')
                lines.append(f"{name}_sum {_fmt_num(fam['sum'])}")
                lines.append(f"{name}_count {int(fam['count'])}")
            else:
                for lbl, v in fam["samples"]:
                    lines.append(f"{name}{_fmt_labels(lbl)} {_fmt_num(v)}")
        return "\n".join(lines) + "\n"


def _esc_help(s):
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s):
    return (str(s).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(lbl):
    if not lbl:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"' for k, v in sorted(
        lbl.items()))
    return "{" + inner + "}"


def _fmt_num(v):
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


#: The process-wide default registry every helper below targets.
REGISTRY = MetricsRegistry()


def counter(name, help="", labelnames=()):
    return Counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return Gauge(name, help, labelnames)


def histogram(name, help="", buckets=DEFAULT_LATENCY_BUCKETS,
              window=None):
    return Histogram(name, help, buckets, window)


def register_collector(fn, name=None):
    return REGISTRY.collector(fn, name)


def snapshot():
    return REGISTRY.snapshot()


def to_prometheus():
    return REGISTRY.to_prometheus()
