"""Autoregressive generation with static KV cache.

Reference pairing: PaddleNLP GenerationMixin.generate. Greedy decode with
the jitted cache path must match the naive full-context argmax loop.
"""
import dataclasses

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

CFG = dataclasses.replace(LLAMA_TINY, dtype="float32", num_hidden_layers=2)


def _naive_greedy(model, ids, n):
    ids = np.asarray(ids)
    for _ in range(n):
        logits = model(paddle.to_tensor(ids.astype(np.int32)))
        nxt = np.asarray(logits._data)[:, -1].argmax(-1)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return ids


def test_greedy_matches_full_context():
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    model.eval()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, CFG.vocab_size, (2, 9)).astype(np.int32)
    want = _naive_greedy(model, prompt, 6)
    got = model.generate(paddle.to_tensor(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got._data), want)


def test_eos_freezes_row():
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    model.eval()
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, CFG.vocab_size, (1, 5)).astype(np.int32)
    ref = np.asarray(model.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=8)._data)
    eos = int(ref[0, 5 + 2])  # treat the 3rd generated token as "eos"
    got = np.asarray(model.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=8,
                                    eos_token_id=eos)._data)
    # identical until eos appears, then frozen at eos
    np.testing.assert_array_equal(got[0, :5 + 3], ref[0, :5 + 3])
    assert np.all(got[0, 5 + 3:] == eos) or got.shape == ref.shape


def test_sampled_generation_runs():
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    model.eval()
    prompt = np.zeros((2, 4), np.int32)
    out = model.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                         do_sample=True, top_k=8, temperature=0.8, seed=3)
    arr = np.asarray(out._data)
    assert arr.shape == (2, 9)
    assert (arr[:, :4] == 0).all()
    assert (arr >= 0).all() and (arr < CFG.vocab_size).all()


def test_gpt_greedy_matches_full_context():
    from paddle_tpu.text.models.gpt import GPT_TINY, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPT_TINY)
    model.eval()
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, GPT_TINY.vocab_size, (2, 7)).astype(np.int32)
    want = _naive_greedy(model, prompt, 5)
    got = model.generate(paddle.to_tensor(prompt), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got._data), want)


def _seq_logprob(model, full, prompt_len):
    """Sum log p(token_t | prefix) for the generated continuation."""
    logits = np.asarray(model(paddle.to_tensor(
        full.astype(np.int32)))._data).astype(np.float64)
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    total = 0.0
    for t in range(prompt_len, full.shape[1]):
        total += logp[0, t - 1, full[0, t]]
    return total


def test_beam_search_beats_or_matches_greedy():
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    model.eval()
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, CFG.vocab_size, (1, 6)).astype(np.int32)

    greedy = np.asarray(model.generate(paddle.to_tensor(prompt),
                                       max_new_tokens=5)._data)
    beam = np.asarray(model.generate(paddle.to_tensor(prompt),
                                     max_new_tokens=5, num_beams=4)._data)
    assert beam.shape == greedy.shape
    sg = _seq_logprob(model, greedy, 6)
    sb = _seq_logprob(model, beam, 6)
    assert sb >= sg - 1e-6, (sb, sg)


def test_beam_one_equals_greedy():
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    model.eval()
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG.vocab_size, (2, 5)).astype(np.int32)
    from paddle_tpu.text.generation import beam_search_generate

    greedy = np.asarray(model.generate(paddle.to_tensor(prompt),
                                       max_new_tokens=4)._data)
    beam1 = np.asarray(beam_search_generate(model,
                                            paddle.to_tensor(prompt),
                                            max_new_tokens=4,
                                            num_beams=1)._data)
    np.testing.assert_array_equal(beam1, greedy)


def test_top_p_sampling_restricts_support():
    """With a tiny top_p every sampled token must be the argmax; the
    nucleus filter is verified directly against a hand computation."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from paddle_tpu.text.generation import _nucleus_filter
    from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    filtered = _nucleus_filter(logits, 0.6)
    # cum-exclusive: [0, .5, .8, .95] -> keep p<0.6: first two tokens
    assert bool(jnp.isfinite(filtered[0, 0]))
    assert bool(jnp.isfinite(filtered[0, 1]))
    assert not bool(jnp.isfinite(filtered[0, 2]))
    assert not bool(jnp.isfinite(filtered[0, 3]))
    # exact nucleus under ties: only ONE of the tied 0.4s survives
    tied = jnp.log(jnp.asarray([[0.4, 0.4, 0.2]]))
    ft = _nucleus_filter(tied, 0.3)
    assert int(jnp.isfinite(ft).sum()) == 1
    # top_p = 0 still keeps the argmax
    f0 = _nucleus_filter(logits, 0.0)
    assert int(jnp.isfinite(f0).sum()) == 1 and bool(
        jnp.isfinite(f0[0, 0]))

    paddle.seed(0)
    cfg = dataclasses.replace(LLAMA_TINY, dtype="float32")
    lm = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(
        np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 8)).astype(np.int32))
    greedy = lm.generate(ids, max_new_tokens=6, do_sample=False)
    tiny_p = lm.generate(ids, max_new_tokens=6, do_sample=True,
                         top_p=1e-6, seed=3)
    np.testing.assert_array_equal(greedy.numpy(), tiny_p.numpy())
    # permissive top_p with sampling still produces valid ids
    samp = lm.generate(ids, max_new_tokens=6, do_sample=True,
                       top_p=0.9, seed=3)
    assert samp.numpy().shape == greedy.numpy().shape
    assert int(samp.numpy().max()) < cfg.vocab_size


def test_right_padded_prompts_match_unpadded():
    """pad_token_id: each right-padded row must generate exactly what an
    unpadded single-row call produces (pad KV is never attended, rotary
    positions continue from the row's own prompt length); an explicit
    attention_mask is equivalent."""
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    model.eval()
    rng = np.random.default_rng(7)
    PAD = 0
    lens = [5, 9, 7]
    prompts = [rng.integers(1, CFG.vocab_size, (n,)).astype(np.int32)
               for n in lens]
    L0 = max(lens)
    batch = np.full((len(lens), L0), PAD, np.int32)
    for i, p in enumerate(prompts):
        batch[i, :len(p)] = p

    out = np.asarray(model.generate(paddle.to_tensor(batch),
                                    max_new_tokens=5,
                                    pad_token_id=PAD)._data)
    for i, p in enumerate(prompts):
        want = np.asarray(model.generate(paddle.to_tensor(p[None]),
                                         max_new_tokens=5)._data)[0]
        np.testing.assert_array_equal(out[i, L0:], want[len(p):])

    am = (batch != PAD).astype(np.int32)
    out2 = np.asarray(model.generate(paddle.to_tensor(batch),
                                     max_new_tokens=5,
                                     attention_mask=am)._data)
    np.testing.assert_array_equal(out, out2)


def test_gpt_right_padded_prompts_match_unpadded():
    """gpt_generate carries the same pad_token_id keyword (API symmetry)
    with the same per-row semantics."""
    from paddle_tpu.text.models.gpt import GPT_TINY, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPT_TINY)
    model.eval()
    rng = np.random.default_rng(8)
    PAD = 0
    lens = [4, 7]
    prompts = [rng.integers(1, GPT_TINY.vocab_size, (n,)).astype(np.int32)
               for n in lens]
    batch = np.full((2, 7), PAD, np.int32)
    for i, p in enumerate(prompts):
        batch[i, :len(p)] = p
    out = np.asarray(model.generate(paddle.to_tensor(batch),
                                    max_new_tokens=4,
                                    pad_token_id=PAD)._data)
    for i, p in enumerate(prompts):
        want = np.asarray(model.generate(paddle.to_tensor(p[None]),
                                         max_new_tokens=4)._data)[0]
        np.testing.assert_array_equal(out[i, 7:], want[len(p):])


def test_top_p_gpt_path():
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64)
    gpt = GPTForCausalLM(cfg)
    ids = paddle.to_tensor(np.random.default_rng(1).integers(
        0, 256, (2, 6)).astype(np.int32))
    greedy = gpt.generate(ids, max_new_tokens=5, do_sample=False)
    tiny_p = gpt.generate(ids, max_new_tokens=5, do_sample=True,
                          top_p=1e-6, seed=5)
    np.testing.assert_array_equal(greedy.numpy(), tiny_p.numpy())
