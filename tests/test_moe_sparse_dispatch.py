"""Sort-based MoE dispatch (round-2 verdict #5).

The sparse path must (a) match the dense [S,E,C] einsum path numerically —
same capacity priority, same renormalized combine weights — and (b) never
materialize an S*E*C intermediate (peak-memory assertion via the compiled
HLO's buffer sizes).

Reference: incubate/distributed/models/moe/moe_layer.py:244 (index-op
dispatch), gate/gshard_gate.py (capacity priority).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.nn.moe import MoELayer, SwitchGate, TopKGate, _topk_gating, \
    _topk_gating_sparse


def _routing_dense(logits, k, C):
    """Collapse the dense dispatch/combine to per-(token, expert) combine
    weight for comparison."""
    dispatch, combine, aux = _topk_gating(logits, k, C)
    return np.asarray(combine.sum(axis=-1)), float(aux)


def _routing_sparse(logits, k, C):
    S, E = logits.shape
    e_flat, sort_idx, starts, counts, slot, w, keep, aux = \
        _topk_gating_sparse(logits, k, C)
    out = np.zeros((S, E), np.float32)
    token = np.tile(np.arange(S), k)
    wk = np.asarray(w * keep)
    for j in range(k * S):
        out[token[j], int(e_flat[j])] += wk[j]
    return out, float(aux)


@pytest.mark.parametrize("k", [1, 2])
@pytest.mark.parametrize("seed", [0, 3])
def test_gating_parity_dense_vs_sparse(k, seed):
    rng = np.random.default_rng(seed)
    S, E = 64, 8
    C = 12  # tight: forces real capacity drops
    logits = jnp.asarray(rng.standard_normal((S, E)), jnp.float32)
    wd, auxd = _routing_dense(logits, k, C)
    ws, auxs = _routing_sparse(logits, k, C)
    np.testing.assert_allclose(ws, wd, atol=1e-5)
    assert abs(auxd - auxs) < 1e-5


@pytest.mark.parametrize("k", [1, 2])
def test_layer_parity_dense_vs_sparse(k):
    paddle_tpu.seed(0)
    d, ff, E = 16, 32, 8
    gate_cls = SwitchGate if k == 1 else TopKGate
    kwargs = {} if k == 1 else {"k": k}
    layer = MoELayer(d, ff, E, dispatch_mode="dense",
                     gate=gate_cls(d, E, **kwargs))
    rng = np.random.default_rng(0)
    x = paddle_tpu.to_tensor(
        rng.standard_normal((2, 32, d)).astype(np.float32))
    layer.dispatch_mode = "dense"
    y_dense = np.asarray(layer(x)._data)
    aux_dense = float(np.asarray(layer.aux_loss._data))
    layer.dispatch_mode = "sparse"
    y_sparse = np.asarray(layer(x)._data)
    aux_sparse = float(np.asarray(layer.aux_loss._data))
    np.testing.assert_allclose(y_sparse, y_dense, atol=2e-5)
    assert abs(aux_dense - aux_sparse) < 1e-5


def test_sparse_grads_match_dense():
    paddle_tpu.seed(1)
    d, ff, E, k = 8, 16, 4, 2
    S = 32
    rng = np.random.default_rng(1)
    gate_w = jnp.asarray(rng.standard_normal((d, E)), jnp.float32) * 0.3
    wu = jnp.asarray(rng.standard_normal((E, d, ff)), jnp.float32) * 0.1
    wd_ = jnp.asarray(rng.standard_normal((E, ff, d)), jnp.float32) * 0.1
    x = jnp.asarray(rng.standard_normal((S, d)), jnp.float32)
    C = 12

    def dense_loss(wu, wd_):
        disp, comb, aux = _topk_gating(x @ gate_w, k, C)
        e_in = jnp.einsum("sd,sec->ecd", x, disp)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", e_in, wu))
        e_out = jnp.einsum("ecf,efd->ecd", h, wd_)
        return jnp.einsum("ecd,sec->sd", e_out, comb).sum() + aux

    def sparse_loss(wu, wd_):
        e_flat, sort_idx, starts, counts, slot, w, keep, aux = \
            _topk_gating_sparse(x @ gate_w, k, C)
        kS = k * S
        gpos = starts[:, None] + jnp.arange(C)[None, :]
        valid = jnp.arange(C)[None, :] < jnp.minimum(counts, C)[:, None]
        a_id = sort_idx[jnp.clip(gpos, 0, kS - 1)]
        e_in = x[a_id % S] * valid[..., None].astype(x.dtype)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", e_in, wu))
        e_out = jnp.einsum("ecf,efd->ecd", h, wd_)
        picked = e_out.reshape(E * C, d)[
            jnp.clip(e_flat * C + slot, 0, E * C - 1)]
        wk = (w * keep).astype(x.dtype)
        return (picked * wk[:, None]).reshape(k, S, d).sum(
            axis=0).sum() + aux

    gd = jax.grad(dense_loss, argnums=(0, 1))(wu, wd_)
    gs = jax.grad(sparse_loss, argnums=(0, 1))(wu, wd_)
    np.testing.assert_allclose(np.asarray(gs[0]), np.asarray(gd[0]),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gs[1]), np.asarray(gd[1]),
                               atol=1e-4)


def test_sparse_path_never_materializes_SEC():
    """Compile the sparse forward at a shape where S*E*C would be ~134M
    fp32 elements and assert no HLO buffer anywhere near that size;
    routing buffers stay O(kS) / O(E*C*d)."""
    S, d, ff, E, k = 4096, 64, 128, 64, 2
    C = max(4, int(np.ceil(k * S * 1.25 / E)))        # 160
    sec_bytes = S * E * C * 4                          # ~167 MB fp32

    def fwd(x, gate_w, wu, wd_):
        e_flat, sort_idx, starts, counts, slot, w, keep, aux = \
            _topk_gating_sparse(x @ gate_w, k, C)
        kS = k * S
        gpos = starts[:, None] + jnp.arange(C)[None, :]
        valid = jnp.arange(C)[None, :] < jnp.minimum(counts, C)[:, None]
        a_id = sort_idx[jnp.clip(gpos, 0, kS - 1)]
        e_in = x[a_id % S] * valid[..., None].astype(x.dtype)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", e_in, wu))
        e_out = jnp.einsum("ecf,efd->ecd", h, wd_)
        picked = e_out.reshape(E * C, d)[
            jnp.clip(e_flat * C + slot, 0, E * C - 1)]
        wk = (w * keep).astype(x.dtype)
        return (picked * wk[:, None]).reshape(k, S, d).sum(axis=0)

    rng = np.random.default_rng(0)
    args = (jnp.asarray(rng.standard_normal((S, d)), jnp.float32),
            jnp.asarray(rng.standard_normal((d, E)), jnp.float32),
            jnp.asarray(rng.standard_normal((E, d, ff)), jnp.float32) * .1,
            jnp.asarray(rng.standard_normal((E, ff, d)), jnp.float32) * .1)
    compiled = jax.jit(fwd).lower(*args).compile()
    analysis = compiled.memory_analysis()
    peak = (analysis.temp_size_in_bytes + analysis.output_size_in_bytes)
    # the whole temp footprint must be far below one S*E*C buffer
    assert peak < sec_bytes // 2, (
        f"sparse path peak {peak / 1e6:.0f} MB vs S*E*C "
        f"{sec_bytes / 1e6:.0f} MB — dense intermediate leaked in")


def test_auto_mode_picks_sparse_at_scale():
    layer = MoELayer(8, 16, 64, dispatch_mode="auto")
    S = 4096
    C = layer.gate.capacity(S)
    assert S * 64 * C > MoELayer.DENSE_DISPATCH_LIMIT
    small_S = 64
    assert small_S * 64 * layer.gate.capacity(small_S) \
        <= MoELayer.DENSE_DISPATCH_LIMIT
