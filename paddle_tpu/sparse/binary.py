"""Sparse binary ops.

Reference: python/paddle/incubate/sparse/binary.py. TPU-native design:
sparse @ dense is gather-rows → scale → segment-sum — the only sparse
matmul shape XLA handles well on TPU (no native sparse MXU path); the
pattern algebra (union/merge for elementwise ops) happens host-side in
numpy at op-build time, while all value math stays on device and on the
autograd tape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply
from .tensor import SparseCooTensor, SparseCsrTensor, is_sparse


def _coo(x) -> SparseCooTensor:
    return x.to_sparse_coo() if isinstance(x, SparseCsrTensor) \
        else x.coalesce()


def matmul(x, y, name=None):
    """sparse (COO/CSR) @ dense. Reference: sparse/binary.py::matmul."""
    if not is_sparse(x):
        raise TypeError("sparse.matmul expects sparse lhs")
    yt = y if isinstance(y, Tensor) else Tensor(y)
    c = _coo(x)
    if len(c.shape) != 2 or yt.ndim not in (1, 2):
        raise ValueError("sparse.matmul supports 2-D sparse @ 1/2-D dense")
    rows, cols = c._indices[0], c._indices[1]
    m = c.shape[0]

    def _mm(vals, dense):
        gathered = dense[cols]  # (nnz, n) or (nnz,)
        scaled = gathered * (vals[:, None] if dense.ndim == 2 else vals)
        return jax.ops.segment_sum(scaled, rows, num_segments=m)

    return apply(_mm, c._values, yt)


def mv(x, vec, name=None):
    """sparse matrix @ dense vector. Reference: sparse/binary.py::mv."""
    return matmul(x, vec, name=name)


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) sampled at ``mask``'s sparsity pattern (SDDMM).
    Reference: sparse/binary.py::masked_matmul."""
    xt = x if isinstance(x, Tensor) else Tensor(x)
    yt = y if isinstance(y, Tensor) else Tensor(y)
    if not is_sparse(mask):
        raise TypeError("mask must be sparse")
    want_csr = isinstance(mask, SparseCsrTensor)
    c = _coo(mask)
    rows, cols = c._indices[0], c._indices[1]

    vals = apply(lambda a, b: jnp.einsum("nk,nk->n", a[rows], b.T[cols]),
                 xt, yt)
    out = SparseCooTensor(c._indices, vals,
                          [xt.shape[0], yt.shape[1]], coalesced=True)
    return out.to_sparse_csr() if want_csr else out


def _merge_patterns(a: SparseCooTensor, b: SparseCooTensor):
    """Union of two coalesced COO patterns → (union_idx, map_a, map_b)."""
    sp = tuple(a.shape[:a.sparse_dim])
    fa = np.ravel_multi_index(np.asarray(a._indices), sp)
    fb = np.ravel_multi_index(np.asarray(b._indices), sp)
    union = np.union1d(fa, fb)
    return (np.stack(np.unravel_index(union, sp)),
            np.searchsorted(union, fa), np.searchsorted(union, fb))


def _ew(op_name, jfn):
    def fn(x, y, name=None):
        if not (is_sparse(x) and is_sparse(y)):
            raise TypeError(f"sparse.{op_name} expects two sparse tensors")
        if list(x.shape) != list(y.shape):
            raise ValueError("shape mismatch")
        want_csr = isinstance(x, SparseCsrTensor)
        a, b = _coo(x), _coo(y)
        idx, ma, mb = _merge_patterns(a, b)
        ma_j, mb_j = jnp.asarray(ma), jnp.asarray(mb)
        n = idx.shape[1]

        def _combine(va, vb):
            za = jnp.zeros((n,) + va.shape[1:], va.dtype).at[ma_j].set(va)
            zb = jnp.zeros((n,) + vb.shape[1:], vb.dtype).at[mb_j].set(vb)
            return jfn(za, zb)

        vals = apply(_combine, a._values, b._values)
        out = SparseCooTensor(idx, vals, x.shape, coalesced=True)
        return out.to_sparse_csr() if want_csr else out
    fn.__name__ = op_name
    fn.__doc__ = (f"Element-wise sparse {op_name} over the union pattern "
                  "(reference: sparse/binary.py).")
    return fn


add = _ew("add", jnp.add)
subtract = _ew("subtract", jnp.subtract)
multiply = _ew("multiply", jnp.multiply)
divide = _ew("divide", jnp.divide)
