"""TPU-native parameter-server analog (round-2 verdict #2).

Reference: distributed/ps/the_one_ps.py (SparseTable row shards over
pservers), fleet/data_generator. Here: mesh-row-sharded embedding tables,
lazy sparse-row Adam, CTR models (wide&deep / DeepFM), and the
data_generator → InMemoryDataset → padded-dense batch pipeline.
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import optimizer as optim
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.fleet.data_generator import (
    MultiSlotDataGenerator)
from paddle_tpu.distributed.ps import ShardedEmbedding
from paddle_tpu.rec import DeepFM, WideDeep
from paddle_tpu.rec.data import (CriteoLineParser, CTRSchema,
                                 iter_ctr_batches, synthetic_ctr_lines)

VOCAB = 4096
SLOTS = 26
DENSE = 13


def _fleet_ctr(model_cls, sharding_degree, vocab=VOCAB, steps=3,
               lazy=True, batch=None):
    paddle_tpu.seed(0)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1,
                               "sharding_degree": sharding_degree}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(
        model_cls(vocab, SLOTS, embed_dim=8, dense_dim=DENSE,
                  hidden=(32, 16)))
    opt = fleet.distributed_optimizer(
        optim.AdamW(learning_rate=1e-2, lazy_mode=lazy,
                    parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(
        model, lambda m, ids, dense, label: m(ids, dense, labels=label)[1])
    if batch is None:
        schema = CTRSchema([f"C{i+1}" for i in range(SLOTS)],
                           ids_per_slot=1, dense_dim=DENSE,
                           vocab_size=vocab)
        parse = CriteoLineParser()
        samples = [parse(l) for l in synthetic_ctr_lines(64)]
        batch = schema.assemble(samples[:16])
    ids = paddle_tpu.to_tensor(batch["ids"])
    dense = paddle_tpu.to_tensor(batch["dense"])
    label = paddle_tpu.to_tensor(batch["label"])
    losses = [float(np.asarray(step(ids, dense, label)._data))
              for _ in range(steps)]
    return losses, model


@pytest.mark.slow   # unblocked by the PR-12 Tensor-pytree fix; multi-
# second GSPMD compile load — slow lane per the tier-1 fast-test budget
def test_table_row_sharded_over_mesh():
    """The table's rows live sharded over the mesh: each device holds
    V/8 rows — a table 8x bigger than one device could replicate."""
    losses, model = _fleet_ctr(WideDeep, sharding_degree=8)
    table = model.embedding.weight._data
    assert str(table.sharding.spec[0]) == "sharding"
    shard_rows = {s.data.shape[0] for s in table.addressable_shards}
    assert shard_rows == {VOCAB // 8}, shard_rows
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("model_cls", [WideDeep, DeepFM])
def test_sharded_matches_single_device(model_cls):
    """Row-sharding is numerically invisible: losses match a
    single-device (replicated) run step for step."""
    l_sharded, _ = _fleet_ctr(model_cls, sharding_degree=4)
    l_single, _ = _fleet_ctr(model_cls, sharding_degree=1)
    np.testing.assert_allclose(l_sharded, l_single, rtol=2e-4, atol=2e-5)


def test_lazy_rows_untouched_in_train_step():
    """Rows whose ids never appear in the batch keep exact initial values
    (lazy sparse-row Adam through the compiled step)."""
    losses, model = _fleet_ctr(WideDeep, sharding_degree=2, steps=2)
    table = np.asarray(model.embedding.weight._data)
    paddle_tpu.seed(0)
    ref = WideDeep(VOCAB, SLOTS, embed_dim=8, dense_dim=DENSE,
                   hidden=(32, 16))
    init = np.asarray(ref.embedding.weight._data)
    unchanged = np.all(table == init, axis=1)
    # the 16x26 batch touches at most 416 distinct rows of 4096
    assert unchanged.sum() >= VOCAB - 16 * SLOTS - 1
    assert (~unchanged).sum() > 0


def test_non_lazy_decay_touches_all_rows():
    l, model = _fleet_ctr(WideDeep, sharding_degree=2, steps=2, lazy=False)
    # AdamW weight decay moves every row when lazy_mode is off
    table = np.asarray(model.embedding.weight._data)
    paddle_tpu.seed(0)
    ref = WideDeep(VOCAB, SLOTS, embed_dim=8, dense_dim=DENSE,
                   hidden=(32, 16))
    init = np.asarray(ref.embedding.weight._data)
    changed = ~np.all(table == init, axis=1)
    assert changed.mean() > 0.99


@pytest.mark.slow   # unblocked by the PR-12 Tensor-pytree fix; multi-
# second GSPMD compile load — slow lane per the tier-1 fast-test budget
def test_ctr_model_learns_signal():
    """End-to-end: generator → dataset → batches → compiled train step;
    the synthetic signal (dense[0] + C1 parity) is learnable."""
    from paddle_tpu.distributed.ps_dataset import InMemoryDataset

    lines = synthetic_ctr_lines(512, seed=1)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "part-0")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")

        class Gen(MultiSlotDataGenerator):
            def generate_sample(self, line):
                parse = CriteoLineParser()

                def g():
                    yield parse(line)
                return g

        ds = InMemoryDataset()
        ds.init(batch_size=64)
        ds.set_filelist([path])
        ds.set_generator(Gen())
        ds.load_into_memory()
        ds.local_shuffle()
        samples = [s for b in ds for s in b]
    assert len(samples) == 512

    schema = CTRSchema([f"C{i+1}" for i in range(SLOTS)], ids_per_slot=1,
                       dense_dim=DENSE, vocab_size=VOCAB)
    paddle_tpu.seed(0)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(
        DeepFM(VOCAB, SLOTS, embed_dim=8, dense_dim=DENSE, hidden=(32,)))
    opt = fleet.distributed_optimizer(
        optim.AdamW(learning_rate=1e-2, lazy_mode=True,
                    parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(
        model, lambda m, ids, dense, label: m(ids, dense, labels=label)[1])
    first = last = None
    for epoch in range(6):
        for b in iter_ctr_batches(iter(samples), schema, 64):
            loss = float(np.asarray(
                step(paddle_tpu.to_tensor(b["ids"]),
                     paddle_tpu.to_tensor(b["dense"]),
                     paddle_tpu.to_tensor(b["label"]))._data))
            if first is None:
                first = loss
            last = loss
    assert last < first * 0.9, (first, last)


def test_data_generator_text_protocol(capsys):
    class Gen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def g():
                yield [("label", [1]), ("ids", [3, 4])]
            return g

    Gen().run_from_memory(["x"])
    out = capsys.readouterr().out
    assert out == "1 1 2 3 4\n"


def test_entry_attr_configs_still_work():
    from paddle_tpu.distributed.ps_dataset import (CountFilterEntry,
                                                   ProbabilityEntry)
    assert CountFilterEntry(5)._to_attr() == "count_filter_entry:5"
    assert ProbabilityEntry(0.5)._to_attr() == "probability_entry:0.5"


# ---------------------------------------------------------------------------
# geo-SGD (reference distributed/ps/the_one_ps.py:655 geo sparse tables;
# fleet spelling: strategy.a_sync + a_sync_configs["k_steps"] > 0)
# ---------------------------------------------------------------------------


def _geo_step(k_steps, dp=2, sharding=4, lr=1e-2):
    paddle_tpu.seed(0)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": sharding}
    strategy.a_sync = True
    strategy.a_sync_configs = {"k_steps": k_steps}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(
        WideDeep(VOCAB, SLOTS, embed_dim=8, dense_dim=DENSE,
                 hidden=(32, 16)))
    opt = fleet.distributed_optimizer(
        optim.AdamW(learning_rate=lr, lazy_mode=True,
                    parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(
        model, lambda m, ids, dense, label: m(ids, dense, labels=label)[1])
    return step, model


def _ctr_stream(n=512, batch=64, seed=1):
    schema = CTRSchema([f"C{i+1}" for i in range(SLOTS)], ids_per_slot=1,
                       dense_dim=DENSE, vocab_size=VOCAB)
    parse = CriteoLineParser()
    samples = [parse(l) for l in synthetic_ctr_lines(n, seed=seed)]
    return list(iter_ctr_batches(iter(samples), schema, batch))


@pytest.mark.slow   # unblocked by the PR-12 Tensor-pytree fix; multi-
# second GSPMD compile load — slow lane per the tier-1 fast-test budget
def test_geo_ctr_converges_close_to_sync():
    """Geo-mode CTR training converges within tolerance of synchronous
    training on the same data (the_one_ps geo-vs-sync contract)."""
    batches = _ctr_stream()

    def run(step):
        first = last = None
        for _ in range(4):
            for b in batches:
                loss = float(np.asarray(step(
                    paddle_tpu.to_tensor(b["ids"]),
                    paddle_tpu.to_tensor(b["dense"]),
                    paddle_tpu.to_tensor(b["label"]))._data))
                if first is None:
                    first = loss
                last = loss
        return first, last

    geo_step, _ = _geo_step(k_steps=4)
    g_first, g_last = run(geo_step)

    paddle_tpu.seed(0)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(
        WideDeep(VOCAB, SLOTS, embed_dim=8, dense_dim=DENSE,
                 hidden=(32, 16)))
    opt = fleet.distributed_optimizer(
        optim.AdamW(learning_rate=1e-2, lazy_mode=True,
                    parameters=model.parameters()),
        strategy=strategy)
    sync_step = opt.make_train_step(
        model, lambda m, ids, dense, label: m(ids, dense, labels=label)[1])
    s_first, s_last = run(sync_step)

    # both learn the signal, and geo's final loss is within 25% of sync
    assert g_last < g_first * 0.9, (g_first, g_last)
    assert s_last < s_first * 0.9, (s_first, s_last)
    assert g_last < s_last * 1.25 + 0.05, (g_last, s_last)


@pytest.mark.slow   # unblocked by the PR-12 Tensor-pytree fix; multi-
# second GSPMD compile load — slow lane per the tier-1 fast-test budget
def test_geo_staleness_bound():
    """Between merges replicas drift (different microbatches); right
    after every k-th step all replicas hold identical parameters — the
    geo staleness bound."""
    step, _ = _geo_step(k_steps=3)
    batches = _ctr_stream(n=512, batch=64, seed=2)
    impl = step  # GeoSGDTrainStep
    from paddle_tpu.distributed.fleet.comm_efficient import GeoSGDTrainStep
    assert isinstance(impl, GeoSGDTrainStep)
    divs = []
    for i, b in enumerate(batches[:6]):
        impl(paddle_tpu.to_tensor(b["ids"]),
             paddle_tpu.to_tensor(b["dense"]),
             paddle_tpu.to_tensor(b["label"]))
        divs.append(impl.replica_divergence())
    # steps are 1-indexed inside the impl: merges at steps 3 and 6
    assert divs[2] == 0.0 and divs[5] == 0.0, divs
    assert divs[0] > 0.0 and divs[3] > 0.0, divs


@pytest.mark.slow   # unblocked by the PR-12 Tensor-pytree fix; multi-
# second GSPMD compile load — slow lane per the tier-1 fast-test budget
def test_geo_table_rows_stay_sharded():
    """The geo replica axis composes with row sharding: the embedding
    table lives [dp, V/sharding, D] over the dp×sharding mesh."""
    step, model = _geo_step(k_steps=2, dp=2, sharding=4)
    b = _ctr_stream(n=64, batch=64)[0]
    step(paddle_tpu.to_tensor(b["ids"]), paddle_tpu.to_tensor(b["dense"]),
         paddle_tpu.to_tensor(b["label"]))
    table = step._param_vals["embedding.weight"]
    assert table.shape[0] == 2
    spec = table.sharding.spec
    assert tuple(spec)[:2] == ("dp", "sharding"), spec


def test_geo_async_k0_raises():
    import pytest

    with pytest.raises(NotImplementedError, match="async"):
        _geo_step(k_steps=0)
