"""Headline benchmark: Llama-style decoder LM pretraining throughput on one
chip (tokens/sec/chip), the single-chip proxy for BASELINE.json's
Llama-2-7B Fleet sharding-stage3 config. Full 7B dims per layer don't fit a
single chip with Adam fp32 moments, so layer count is scaled down while
keeping the per-layer shapes MXU-saturating; tokens/sec/chip is comparable
round over round.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import glob
import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu
    from paddle_tpu import optimizer as optim
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.text.models.llama import LlamaConfig, LlamaForCausalLM

    backend = jax.default_backend()
    paddle_tpu.seed(0)

    # ~0.5B params: 7B's hidden/head shapes halved, 8 layers; bf16 + flash
    # attention + remat — fits one chip incl. Adam fp32 moments.
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5504, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=16,
                      max_position_embeddings=2048, dtype="bfloat16",
                      remat=True)
    batch, seqlen = 4, 2048
    if backend == "cpu":  # smoke mode off-TPU
        cfg = LlamaConfig(vocab_size=1024, hidden_size=256,
                          intermediate_size=688, num_hidden_layers=2,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=512, dtype="float32")
        batch, seqlen = 2, 128

    strategy = DistributedStrategy()
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(LlamaForCausalLM(cfg))
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())

    opt = fleet.distributed_optimizer(
        optim.AdamW(learning_rate=1e-4, weight_decay=0.01,
                    parameters=model.parameters()),
        strategy=strategy)

    def loss_fn(m, input_ids, labels):
        return m(input_ids, labels=labels)

    step = opt.make_train_step(model, loss_fn)

    rng = np.random.default_rng(0)
    ids = paddle_tpu.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seqlen)).astype(np.int32))
    labels = paddle_tpu.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seqlen)).astype(np.int32))

    # compile + warmup
    loss = step(ids, labels)
    jax.block_until_ready(loss._data)

    n_steps = 10 if backend != "cpu" else 2
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = step(ids, labels)
    jax.block_until_ready(loss._data)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seqlen * n_steps / dt
    # MFU: 6 * n_params FLOPs/token (fwd+bwd), vs 197 TFLOPs bf16 (v5e ref)
    flops_per_tok = 6 * n_params
    mfu = tokens_per_sec * flops_per_tok / 197e12 if backend == "tpu" else 0.0

    vs = 1.0
    best = 0.0
    for f in glob.glob(os.path.join(os.path.dirname(__file__) or ".",
                                    "BENCH_r*.json")):
        try:
            with open(f) as fh:
                rec = json.load(fh)
            best = max(best, float(rec.get("value", 0.0)))
        except Exception:
            pass
    if best > 0:
        vs = tokens_per_sec / best

    print(json.dumps({
        "metric": f"llama-0.5B pretrain tokens/sec/chip "
                  f"(bf16+flash+remat, AdamW, {backend})",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 4),
        "extra": {"params": n_params, "mfu_est_v5e": round(mfu, 4),
                  "loss": float(np.asarray(loss._data)),
                  "batch": batch, "seqlen": seqlen, "steps": n_steps},
    }))


if __name__ == "__main__":
    sys.exit(main())
