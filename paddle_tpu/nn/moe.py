"""Mixture-of-Experts (ERNIE-MoE capability; reference:
python/paddle/incubate/distributed/models/moe/).

TPU-native GShard-style design: experts are ONE batched parameter tensor
[num_experts, ...] and token routing is expressed as dense einsums with a
capacity-bounded one-hot dispatch mask — static shapes, MXU-friendly, and
expert parallelism is just sharding the leading expert axis over the mesh's
"ep" axis (the all-to-all materializes as XLA collectives when the token and
expert shardings differ). This replaces the reference's explicit
c_alltoall + per-expert sub-programs.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..tensor import Tensor, apply
from .initializer import XavierUniform
from .layer_base import Layer


def _topk_gating(logits, k, capacity):
    """Returns (dispatch [S, E, C] bool-ish, combine [S, E, C], aux_loss)."""
    S, E = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)  # [S, E]
    # aux load-balance loss (Switch/GShard): E * sum_e mean_gates_e * mean_frac_e
    topk_val, topk_idx = jax.lax.top_k(gates, k)  # [S, k]
    mask_k = jax.nn.one_hot(topk_idx, E, dtype=gates.dtype)  # [S, k, E]
    frac = jnp.mean(mask_k[:, 0], axis=0)
    aux = E * jnp.sum(jnp.mean(gates, axis=0) * frac)

    # position of each token within its expert queue, per k-choice
    disp = jnp.zeros((S, E), dtype=gates.dtype)
    combine = jnp.zeros((S, E, capacity), dtype=gates.dtype)
    prev_counts = jnp.zeros((E,), dtype=jnp.int32)
    for choice in range(k):
        m = mask_k[:, choice]  # [S, E]
        pos_in_e = (jnp.cumsum(m, axis=0) - m).astype(jnp.int32) + prev_counts[None, :]
        keep = (pos_in_e < capacity) * m
        gate_c = topk_val[:, choice:choice + 1] * keep  # [S, E]
        oh_pos = jax.nn.one_hot(pos_in_e, capacity, dtype=gates.dtype)  # [S,E,C]
        combine = combine + gate_c[..., None] * oh_pos * keep[..., None]
        prev_counts = prev_counts + jnp.sum(m, axis=0).astype(jnp.int32)
    # renormalize combine weights over chosen experts
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    dispatch = (combine > 0).astype(gates.dtype)
    return dispatch, combine, aux


class TopKGate(Layer):
    def __init__(self, d_model, num_experts, k=2, capacity_factor=1.25):
        super().__init__()
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter((d_model, num_experts),
                                            default_initializer=XavierUniform())

    def forward(self, x_flat):
        """x_flat: [S, d] → (dispatch, combine, aux_loss)."""
        S = x_flat.shape[0]
        capacity = max(4, int(math.ceil(self.k * S * self.capacity_factor /
                                        self.num_experts)))
        def f(x, w):
            logits = (x.astype(jnp.float32) @ w.astype(jnp.float32))
            return _topk_gating(logits, self.k, capacity)
        return apply(f, x_flat, self.weight, n_outputs=3)


class SwitchGate(TopKGate):
    def __init__(self, d_model, num_experts, capacity_factor=1.25):
        super().__init__(d_model, num_experts, k=1,
                         capacity_factor=capacity_factor)


class MoELayer(Layer):
    """Expert FFN bank + gate. Experts stored batched: weights [E, d, ff].

    Under fleet expert-parallel the leading E axis is sharded on the mesh
    "ep" axis; XLA turns the dispatch einsum into an all-to-all over ICI.
    """

    def __init__(self, d_model, d_hidden, num_experts, k=2,
                 capacity_factor=1.25, activation="gelu", gate=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.gate = gate or TopKGate(d_model, num_experts, k, capacity_factor)
        self.w_up = self.create_parameter((num_experts, d_model, d_hidden),
                                          default_initializer=XavierUniform())
        self.w_down = self.create_parameter((num_experts, d_hidden, d_model),
                                            default_initializer=XavierUniform())
        self.activation = activation
        self.aux_loss = None

    def forward(self, x):
        """x: [B, L, d] → [B, L, d]; stores aux_loss for the trainer."""
        b, l, d = x.shape
        from ..tensor_ops.manipulation import reshape
        x_flat = reshape(x, (b * l, d))
        dispatch, combine, aux = self.gate(x_flat)
        self.aux_loss = aux

        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "silu": jax.nn.silu}[self.activation]

        def f(xf, disp, comb, wu, wd):
            # [S,d],[S,E,C] -> [E,C,d]: the all-to-all when sharded
            expert_in = jnp.einsum("sd,sec->ecd", xf, disp)
            h = act(jnp.einsum("ecd,edf->ecf", expert_in, wu))
            expert_out = jnp.einsum("ecf,efd->ecd", h, wd)
            return jnp.einsum("ecd,sec->sd", expert_out, comb)

        out = apply(f, x_flat, dispatch, combine, self.w_up, self.w_down)
        return reshape(out, (b, l, d))
