"""bf16 forward+backward sweep over representative layers.

Round-2 regression class: ops that work in fp32 but break under
bfloat16 (conv's preferred_element_type broke the grad transpose rule;
max_pool's np.iinfo crashed on ml_dtypes). Every layer here runs a
full train step in bf16 and must produce finite bf16 outputs and
finite grads.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _bf16_step(layer, x_shape, reduce_to_scalar=None, x=None):
    paddle.seed(0)
    layer.to(dtype="bfloat16")
    rng = np.random.default_rng(0)
    if x is None:
        x = paddle.to_tensor(
            rng.standard_normal(x_shape).astype(np.float32)) \
            .astype("bfloat16")
    out = layer(x)
    if isinstance(out, (tuple, list)):
        out = out[0]
    assert "bfloat16" in str(out.dtype), (layer, out.dtype)
    loss = (out.astype("float32") ** 2).mean() if reduce_to_scalar is None \
        else reduce_to_scalar(out)
    loss.backward()
    for name, p in layer.named_parameters():
        if not p.stop_gradient:
            assert p.grad is not None, f"{type(layer).__name__}.{name}"
            g = np.asarray(p.grad._data, np.float32)
            assert np.isfinite(g).all(), f"{type(layer).__name__}.{name}"
    return out


CASES = [
    (lambda: nn.Linear(8, 16), (2, 8)),
    (lambda: nn.Conv1D(3, 6, 3, padding=1), (2, 3, 10)),
    (lambda: nn.Conv2D(3, 6, 3, padding=1), (2, 3, 8, 8)),
    (lambda: nn.Conv2DTranspose(3, 6, 3, stride=2), (2, 3, 5, 5)),
    (lambda: nn.Conv3D(2, 4, 3, padding=1), (1, 2, 4, 6, 6)),
    (lambda: nn.Sequential(nn.Conv2D(3, 6, 3), nn.MaxPool2D(2)),
     (2, 3, 8, 8)),
    (lambda: nn.Sequential(nn.Conv2D(3, 6, 3), nn.AvgPool2D(2)),
     (2, 3, 8, 8)),
    (lambda: nn.Sequential(nn.Conv2D(3, 6, 3),
                           nn.AdaptiveAvgPool2D(1)), (2, 3, 8, 8)),
    (lambda: nn.BatchNorm2D(4), (2, 4, 6, 6)),
    (lambda: nn.LayerNorm(12), (2, 5, 12)),
    (lambda: nn.GroupNorm(2, 8), (2, 8, 5, 5)),
    (lambda: nn.InstanceNorm2D(4), (2, 4, 6, 6)),
    (lambda: nn.Embedding(20, 8), None),
    (lambda: nn.GRU(6, 8), (2, 5, 6)),
    (lambda: nn.LSTM(6, 8), (2, 5, 6)),
    (lambda: nn.MultiHeadAttention(16, 4), (2, 6, 16)),
    (lambda: nn.TransformerEncoderLayer(16, 4, 32), (2, 6, 16)),
    (lambda: nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Silu(),
                           nn.Hardswish(), nn.Mish()), (2, 8)),
]


@pytest.mark.parametrize(
    "factory,shape", CASES,
    ids=[f"{i}" for i in range(len(CASES))])
def test_bf16_forward_backward(factory, shape):
    layer = factory()
    if isinstance(layer, nn.Embedding):
        ids = paddle.to_tensor(
            np.random.default_rng(1).integers(0, 20, (2, 5))
            .astype(np.int32))
        _bf16_step(layer, None, x=ids)
    else:
        _bf16_step(layer, shape)


def test_bf16_losses():
    paddle.seed(0)
    from paddle_tpu.nn import functional as F

    rng = np.random.default_rng(2)
    logits = paddle.to_tensor(rng.standard_normal((4, 10))
                              .astype(np.float32)).astype("bfloat16")
    labels = paddle.to_tensor(rng.integers(0, 10, (4,)).astype(np.int64))
    for loss in (F.cross_entropy(logits.astype("float32"), labels),
                 F.mse_loss(logits.astype("float32"),
                            paddle.zeros((4, 10)))):
        v = float(np.asarray(loss._data))
        assert np.isfinite(v)


def test_bf16_flash_attention_interpret():
    """The pallas flash kernel must accept bf16 operands (round-2 fix:
    it used to upcast to fp32 before the MXU dots)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((1, 128, 2, 64)),
                    dtype=jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, 128, 2, 64)),
                    dtype=jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 128, 2, 64)),
                    dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())
