"""incubate.passes — reference spelling (reference
python/paddle/incubate/passes/ip.py IR pass helpers). The TPU stack's
pass surface is distributed.passes (strategy-mutating passes; graph
rewriting is XLA's job), re-exported here."""
from ...distributed.passes import PassContext, PassManager, new_pass  # noqa: F401
