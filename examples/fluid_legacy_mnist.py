"""Classic 1.x-era fluid script, running unmodified on paddle_tpu.

A fluid static program (data/fc/Executor workflow) with a Switch-based
piecewise LR schedule and an inference-model export — the shape of
thousands of pre-2.0 Paddle training scripts.

Run: python examples/fluid_legacy_mnist.py  (CPU ok; forces cpu platform)
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def build_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        feat = fluid.nets.simple_img_conv_pool(
            img, num_filters=8, filter_size=5, pool_size=2, pool_stride=2,
            conv_padding=2, act="relu")
        logits = layers.fc(feat, size=10)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)

        step = layers.autoincreased_step_counter()
        lr = layers.fill_constant([1], "float32", 0.0)
        with layers.Switch() as sw:
            with sw.case(layers.less_than(
                    layers.cast(step, "float32"),
                    layers.fill_constant([1], "float32", 30.0))):
                layers.assign(layers.fill_constant([1], "float32", 0.1), lr)
            with sw.default():
                layers.assign(layers.fill_constant([1], "float32", 0.02), lr)
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.05, momentum=0.9).minimize(loss)
    return main, startup, img, logits, loss, acc, lr


def main():
    main_prog, startup, img, logits, loss, acc, lr = build_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    rng = np.random.default_rng(0)
    # synthetic MNIST-shaped data: class k = noisy constant image k/10
    ys = rng.integers(0, 10, (256, 1)).astype(np.int64)
    xs = (ys[:, :, None, None] / 10.0
          + 0.1 * rng.standard_normal((256, 1, 28, 28))).astype(np.float32)

    for epoch in range(3):
        for i in range(0, 256, 64):
            feed = {"img": xs[i:i + 64], "label": ys[i:i + 64]}
            lv, av, lrv = exe.run(main_prog, feed=feed,
                                  fetch_list=[loss, acc, lr])
            if i == 0:
                print(f"epoch {epoch}: loss={float(np.asarray(lv).reshape(-1)[0]):.4f} "
                      f"acc={float(np.asarray(av)):.3f} "
                      f"lr={float(np.asarray(lrv).reshape(-1)[0]):.3f}")

    import tempfile
    with tempfile.TemporaryDirectory() as td:
        fluid.io.save_inference_model(td, ["img"], [logits], exe,
                                      main_program=main_prog)
        prog, feeds, fetches = fluid.io.load_inference_model(td, exe)
        (out,) = exe.run(prog, feed={feeds[0]: xs[:4]}, fetch_list=fetches)
        print("inference model reloaded; logits:", out.shape)


if __name__ == "__main__":
    main()
