"""Reference: python/paddle/utils/image_util.py (the pre-2.0 image
helpers: resize_image/flip/crop_img/oversample in CHW float layout).
Pixel work delegates to dataset/image.py (numpy/PIL, no cv2)."""
from __future__ import annotations

import numpy as np

from ..dataset import image as _img

__all__ = ["resize_image", "flip", "crop_img", "load_image", "oversample"]


def resize_image(img, target_size):
    """Resize short edge to target_size (HWC uint8/float numpy in,
    same layout out)."""
    return _img.resize_short(np.asarray(img), target_size)


def flip(im):
    """Horizontal flip of a CHW image (reference operates on CHW)."""
    im = np.asarray(im)
    return im[:, :, ::-1] if im.ndim == 3 else im[:, ::-1]


def crop_img(im, inner_size, color=True, test=True):
    """Center (test) or random (train) crop of an HWC image."""
    if test:
        return _img.center_crop(im, inner_size, color)
    return _img.random_crop(im, inner_size, color)


def load_image(img_path, is_color=True):
    return _img.load_image(img_path, is_color)


def oversample(img, crop_dims):
    """10-crop oversampling (4 corners + center, mirrored) of HWC images.

    img: list/array of HWC images; returns stacked crops
    (reference image_util.py:146).
    """
    imgs = [np.asarray(i) for i in img]
    im_shape = imgs[0].shape
    crop_dims = np.asarray(crop_dims)
    im_center = np.asarray(im_shape[:2]) / 2.0

    h_indices = (0, im_shape[0] - crop_dims[0])
    w_indices = (0, im_shape[1] - crop_dims[1])
    crops_ix = np.empty((5, 4), dtype=int)
    curr = 0
    for i in h_indices:
        for j in w_indices:
            crops_ix[curr] = (i, j, i + crop_dims[0], j + crop_dims[1])
            curr += 1
    crops_ix[4] = np.tile(im_center, (1, 2)) + np.concatenate(
        [-crop_dims / 2.0, crop_dims / 2.0])
    crops_ix = np.tile(crops_ix, (2, 1))

    crops = np.empty((10 * len(imgs), crop_dims[0], crop_dims[1],
                      im_shape[-1]), dtype=imgs[0].dtype)
    ix = 0
    for im in imgs:
        for crop in crops_ix:
            crops[ix] = im[crop[0]:crop[2], crop[1]:crop[3], :]
            ix += 1
        crops[ix - 5:ix] = crops[ix - 5:ix, :, ::-1, :]  # mirror last 5
    return crops
