from . import datasets, models, transforms  # noqa: F401
from .ops import nms, roi_align  # noqa: F401
