"""jit.save / jit.load (reference: python/paddle/jit/api.py save/load).

The reference serializes a static Program + params. Our compiled artifact is
an XLA computation: we save (a) the layer state_dict and (b) when
jax.export is available, the StableHLO of the traced forward, giving an
inference artifact loadable without the original python class.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor


def build_symbolic_specs(shapes, dtypes):
    """ShapeDtypeStructs for jax.export with symbolic dynamic dims.

    Dims given as None/-1 become symbolic; dim 0 shares one symbol across
    inputs so batch-paired inputs stay unified, later dims get per-input
    symbols (src_len/tgt_len aren't forced equal).
    """
    from jax import export as jax_export

    scope = jax_export.SymbolicScope()
    out = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        dims = []
        for j, d in enumerate(shape):
            dynamic = d is None or (isinstance(d, int) and d < 0)
            dims.append(("d0" if j == 0 else f"d{i}_{j}")
                        if dynamic else str(d))
        shp = jax_export.symbolic_shape(",".join(dims), scope=scope)
        out.append(jax.ShapeDtypeStruct(shp, dtype))
    return out


def save(layer, path, input_spec=None, **configs):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {"format": "paddle_tpu.jit", "version": 1}
    if configs:
        # reference jit.save forwards extra configs into the program desc;
        # here they ride in the payload (serving.save_lm stores the LM
        # config/arch this way for inference.create_llm_predictor)
        payload["configs"] = dict(configs)
    from ..nn.layer_base import Layer

    if isinstance(layer, Layer):
        payload["state_dict"] = {
            k: np.asarray(v._data) for k, v in layer.state_dict().items()
        }
        payload["class"] = type(layer).__module__ + "." + type(layer).__qualname__
    hlo = None
    if input_spec is None and isinstance(layer, Layer):
        # reference jit.save without input_spec exports the
        # concrete_program traced by earlier forward calls; the
        # StaticFunction remembers its last all-Tensor call signature
        last = getattr(getattr(layer, "forward", None), "_last_args", None)
        if last:
            from ..static import InputSpec

            input_spec = [InputSpec(shape=list(s.shape), dtype=s.dtype)
                          for s in last]
    if input_spec is not None:
        try:
            from jax import export as jax_export
            shapes = build_symbolic_specs(
                [tuple(s.shape) for s in input_spec],
                [s.dtype for s in input_spec])

            def fwd(*xs):
                out = layer(*[Tensor(x) for x in xs])
                return jax.tree_util.tree_map(
                    lambda o: o._data if isinstance(o, Tensor) else o, out,
                    is_leaf=lambda o: isinstance(o, Tensor))
            exported = jax_export.export(jax.jit(fwd))(*shapes)
            hlo = exported.serialize()
            payload["input_names"] = [
                (s.name if getattr(s, "name", None) else f"x{i}")
                for i, s in enumerate(input_spec)]
            payload["output_names"] = [
                f"out{i}" for i in range(len(exported.out_avals))]
        except Exception as e:
            import warnings
            warnings.warn(f"jit.save: StableHLO export failed ({e}); "
                          "artifact will carry weights only")
            hlo = None
    payload["stablehlo"] = hlo
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(payload, f)
    return path + ".pdmodel"


class TranslatedLayer:
    """Inference-only callable rebuilt from a serialized artifact."""

    def __init__(self, payload):
        self._payload = payload
        self._callable = None
        self.n_inputs = None
        self.input_avals = None
        self.output_avals = None
        self.input_names = payload.get("input_names")
        self.output_names = payload.get("output_names")
        self.configs = payload.get("configs", {})
        if payload.get("stablehlo"):
            from jax import export as jax_export
            exported = jax_export.deserialize(payload["stablehlo"])
            self._callable = exported.call
            self.input_avals = exported.in_avals
            self.output_avals = exported.out_avals
            self.n_inputs = len(exported.in_avals)
            if self.input_names is None:
                self.input_names = [f"x{i}" for i in range(self.n_inputs)]
            if self.output_names is None:
                self.output_names = [
                    f"out{i}" for i in range(len(exported.out_avals))]

    def state_dict(self):
        return {k: Tensor(jnp.asarray(v))
                for k, v in self._payload.get("state_dict", {}).items()}

    def __call__(self, *args):
        if self._callable is None:
            raise RuntimeError(
                "artifact has no compiled graph; re-save with input_spec or "
                "rebuild the Layer class and use set_state_dict")
        raw = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._callable(*raw)
        return jax.tree_util.tree_map(Tensor, out)


def load(path, **configs):
    p = path if path.endswith(".pdmodel") else path + ".pdmodel"
    with open(p, "rb") as f:
        payload = pickle.load(f)
    return TranslatedLayer(payload)
