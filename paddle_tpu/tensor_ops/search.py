"""Search/sort ops. Reference: python/paddle/tensor/search.py."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor, apply, nondiff
from ._factory import raw


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        out = jnp.argmax(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out
    return nondiff(f, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        out = jnp.argmin(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out
    return nondiff(f, x)


def argsort(x, axis=-1, descending=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis)
        return jnp.flip(idx, axis=axis) if descending else idx
    return nondiff(f, x)


def sort(x, axis=-1, descending=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis)
        return jnp.flip(out, axis=axis) if descending else out
    return apply(f, x)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    kk = int(raw(k)) if isinstance(k, Tensor) else int(k)
    def f(a):
        ax = axis if axis is not None else -1
        a_m = jnp.moveaxis(a, ax, -1)
        src = a_m if largest else -a_m
        vals, idx = jax.lax.top_k(src, kk)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)
    import jax
    vals, idx = apply(f, x, n_outputs=2)
    idx = Tensor(idx._data, stop_gradient=True)
    return vals, idx


import jax  # noqa: E402  (used inside topk closure)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        vals = jnp.take(s, k - 1, axis=axis)
        idx = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx
    vals, idx = apply(f, x, n_outputs=2)
    return vals, Tensor(idx._data)


def mode(x, axis=-1, keepdim=False, name=None):
    import numpy as np
    a = np.asarray(raw(x))
    ax = axis % a.ndim
    moved = np.moveaxis(a, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals, idxs = [], []
    for row in flat:
        uniq, counts = np.unique(row, return_counts=True)
        v = uniq[np.argmax(counts)]
        vals.append(v)
        idxs.append(int(np.where(row == v)[0][-1]))
    vs = np.asarray(vals).reshape(moved.shape[:-1])
    ix = np.asarray(idxs).reshape(moved.shape[:-1])
    if keepdim:
        vs = np.expand_dims(vs, ax)
        ix = np.expand_dims(ix, ax)
    return Tensor(jnp.asarray(vs)), Tensor(jnp.asarray(ix))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    v = raw(values)

    def f(a):
        if a.ndim <= 1:
            out = jnp.searchsorted(a, v, side=side)
        else:
            # N-D: the last dim is the sorted axis, leading dims batch
            # (reference searchsorted supports batched sequences)
            import jax as _jax
            vv = jnp.asarray(v)
            if vv.shape[:-1] != a.shape[:-1]:
                raise ValueError(
                    f"searchsorted: leading (batch) dims of values "
                    f"{vv.shape} must match sorted_sequence {a.shape}")
            flat_a = a.reshape((-1, a.shape[-1]))
            flat_v = vv.reshape((flat_a.shape[0], -1))
            out = _jax.vmap(
                lambda ar, vr: jnp.searchsorted(ar, vr, side=side))(
                flat_a, flat_v)
            out = out.reshape(vv.shape)
        return out.astype("int32") if out_int32 else out
    return nondiff(f, sorted_sequence)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    seq = raw(sorted_sequence)
    return nondiff(lambda a: jnp.searchsorted(seq, a, side=side), x)


def histogram(input, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h
    return nondiff(f, input)


def bincount(x, weights=None, minlength=0, name=None):
    w = raw(weights) if weights is not None else None
    return nondiff(lambda a: jnp.bincount(a, weights=w, minlength=minlength,
                                          length=None), x)
