"""Shared runtime hooks for the lint tooling.

The retrace and serving-compile checkers both need an XLA compile-event
counter; this is the one shared implementation (previously duplicated in
``tools/check_retrace.py`` and ``tools/check_serving_compiles.py``).
"""
from __future__ import annotations


class CompileEventCounter:
    """Counts backend compile events via the jax monitoring API.

    ``install()`` registers the listener (idempotent per instance) and
    returns self; ``available`` is False when the private monitoring
    module is missing, in which case ``count`` stays 0 and callers
    should treat the signal as absent rather than "no compiles".
    Listener registration is process-global in jax, so installation is
    permanent — use ``reset()`` between measured phases.
    """

    def __init__(self):
        self.count = 0
        self.available = False
        self._installed = False

    def _on_event(self, event, *a, **k):
        if "compil" in event.lower():
            self.count += 1

    def install(self):
        if self._installed:
            return self
        try:
            from jax._src import monitoring
            monitoring.register_event_listener(self._on_event)
            self.available = True
        except Exception as e:  # monitoring API moved/absent
            self.available = False
            self._unavailable_reason = f"{type(e).__name__}: {e}"
        self._installed = True
        return self

    def reset(self):
        self.count = 0
        return self
