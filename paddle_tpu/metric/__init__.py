"""Metrics. Reference: python/paddle/metric/metrics.py."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        p = np.asarray(pred._data if isinstance(pred, Tensor) else pred)
        l = np.asarray(label._data if isinstance(label, Tensor) else label)
        idx = np.argsort(-p, axis=-1)[..., :self.maxk]
        if l.ndim == p.ndim:
            l = l.squeeze(-1)
        correct = idx == l[..., None]
        return Tensor(np.asarray(correct, dtype=np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._data if isinstance(correct, Tensor) else correct)
        num = c.shape[0] if c.ndim > 0 else 1
        flat = c.reshape(-1, c.shape[-1])
        for i, k in enumerate(self.topk):
            self.total[i] += flat[:, :k].sum()
            self.count[i] += flat.shape[0]
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).reshape(-1)
        lab = l.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & lab))
        self.fp += int(np.sum(pred_pos & ~lab))

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).reshape(-1)
        lab = l.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & lab))
        self.fn += int(np.sum(~pred_pos & lab))

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args,
                 **kwargs):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = l.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(int),
                          self.num_thresholds)
        for b, lab in zip(bins, l):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2.0
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    p = np.asarray(input._data)
    l = np.asarray(label._data).reshape(-1)
    idx = np.argsort(-p, axis=-1)[:, :k]
    correct_ = (idx == l[:, None]).any(axis=1)
    return Tensor(np.asarray([correct_.mean()], dtype=np.float32))
