"""fluid.contrib shim: the pieces 2.x-era code reaches for (mixed
precision decorator) re-exported from paddle_tpu.amp/static.amp."""
from ..static import amp  # noqa: F401


class layers:  # contrib.layers namespace stub
    pass
