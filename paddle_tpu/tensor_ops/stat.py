"""Statistics ops. Reference: python/paddle/tensor/stat.py."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor, apply, nondiff
from ._factory import raw, reduce_axis as _axis_arg


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis_arg(axis)
    return apply(lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, name=None):
    if isinstance(axis, list):
        axis = tuple(axis)
    return apply(lambda a: jnp.median(a, axis=axis, keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    if isinstance(axis, list):
        axis = tuple(axis)
    return apply(lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, name=None):
    qq = raw(q)
    if isinstance(qq, (list, tuple)):
        qq = jnp.asarray(qq)
    if isinstance(axis, list):
        axis = tuple(axis)
    return apply(lambda a: jnp.quantile(a, qq, axis=axis, keepdims=keepdim), x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    qq = raw(q)
    if isinstance(qq, (list, tuple)):
        qq = jnp.asarray(qq)
    if isinstance(axis, list):
        axis = tuple(axis)
    return apply(lambda a: jnp.nanquantile(a, qq, axis=axis, keepdims=keepdim), x)


def numel(x, name=None):
    import numpy as np
    if not isinstance(x, Tensor):
        # reference tensor/stat.py numel: check_variable_and_dtype —
        # raw ndarrays/lists are a TypeError, eager and static alike
        raise TypeError(
            f"The type of 'x' in numel must be Tensor, but received "
            f"{type(x)}")
    n = int(np.prod(raw(x).shape)) if raw(x).shape else 1
    from .. import tensor as tensor_mod
    if tensor_mod._op_recorder is not None:
        # static numel/size op emits shape [1] (2.3-era static graphs
        # have no 0-d tensors); eager keeps the modern 0-d result
        return Tensor(jnp.asarray([n]))
    return Tensor(jnp.asarray(n))


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = raw(fweights) if fweights is not None else None
    aw = raw(aweights) if aweights is not None else None
    return apply(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), x)
