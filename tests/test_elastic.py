"""Elastic membership + rejoin with checkpoint-resume (round-2 verdict #6).

Reference: distributed/fleet/elastic/manager.py (etcd membership, watch,
re-rank, restart). Here: file-heartbeat membership, supervisor gang
re-formation with PADDLE_ELASTIC_* env, scale-in re-rank, and
maybe_resume() restoring the last durable checkpoint.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from paddle_tpu.distributed.elastic import ElasticMembership

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_membership_register_peers_lost_rerank(tmp_path):
    a = ElasticMembership(tmp_path, "hostA", timeout=5).register()
    b = ElasticMembership(tmp_path, "hostB", timeout=5).register()
    c = ElasticMembership(tmp_path, "hostC", timeout=5).register()
    assert a.peers() == ["hostA", "hostB", "hostC"]
    assert b.rerank() == (1, 3)
    c.leave()
    assert a.lost(["hostA", "hostB", "hostC"]) == ["hostC"]
    assert a.rerank() == (0, 2)
    # stale heartbeat = lost (etcd lease expiry analog)
    with open(os.path.join(tmp_path, "node.hostB"), "w") as fh:
        fh.write(str(time.time() - 100))
    assert a.peers() == ["hostA"]
    assert a.rerank() == (0, 1)


def test_membership_wait_for_barrier(tmp_path):
    a = ElasticMembership(tmp_path, "n0", timeout=5).register()
    assert not a.wait_for(2, timeout=0.5, poll=0.1)
    ElasticMembership(tmp_path, "n1", timeout=5).register()
    assert a.wait_for(2, timeout=2, poll=0.1)


_WORKER = r'''
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.elastic import attempt_number, maybe_resume

rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
attempt = attempt_number()
out_dir = sys.argv[1]
kill_step = int(sys.argv[2])
mgr = CheckpointManager(os.environ["PADDLE_ELASTIC_CKPT_DIR"],
                        max_to_keep=2)

target = jnp.asarray(np.arange(8, dtype=np.float32))
w0 = jnp.zeros(8)
start, state = maybe_resume(mgr, template={"w": w0, "step": 0})
w = state["w"] if state is not None else w0

losses = []
import time as _t

marker = os.path.join(out_dir, "rank1_dead")
for step in range(start, 10):
    if attempt == 0 and rank == 1 and step == kill_step:
        # die only after the pre-kill checkpoint is durable, so the
        # resume point is deterministic
        deadline = _t.time() + 120
        while (mgr.latest_step() or -1) < kill_step - 1 \
                and _t.time() < deadline:
            _t.sleep(0.1)
        open(marker, "w").close()
        os._exit(17)  # simulated worker death mid-training
    if attempt == 0 and rank == 0 and step >= kill_step + 2:
        # don't outrun the crash: hold here until worker 1 has died (the
        # supervisor will reap us right after)
        deadline = _t.time() + 120
        while not os.path.exists(marker) and _t.time() < deadline:
            _t.sleep(0.1)
        _t.sleep(5)
        break
    loss = float(((w - target) ** 2).sum())
    losses.append(loss)
    w = w - 0.2 * 2 * (w - target)
    if rank == 0:
        mgr.save(step, {"w": w, "step": step}, async_save=False)

with open(os.path.join(out_dir, f"result.rank{rank}.attempt{attempt}.json"),
          "w") as fh:
    json.dump({"start": start, "losses": losses, "world": world,
               "final_loss": float(((w - target) ** 2).sum()),
               "slot": os.environ.get("PADDLE_WORKER_SLOT")}, fh)
'''


@pytest.mark.slow
def test_worker_death_resumes_from_checkpoint(tmp_path):
    """Kill worker 1 at step 5 of 10; the relaunched gang must resume
    from the last checkpoint (not step 0) and keep improving the loss."""
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    out = tmp_path / "out"
    out.mkdir()
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "1", "--elastic",
         "--ckpt_dir", str(ckpt), str(script), str(out), "5"],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "resume from checkpoint" in r.stderr

    a0 = json.load(open(out / "result.rank0.attempt1.json"))
    # worker 1 waited for ckpt-4 to be durable before dying, so the
    # re-formed gang resumes at >= 5 (rank 0 may have checkpointed a bit
    # further before the supervisor reaped it) — never from step 0
    assert 5 <= a0["start"] <= 8, a0
    # no loss regression: at resume the loss must already be at the
    # checkpointed trajectory level (step-5 loss is ~0.3; scratch is 140)
    assert a0["losses"][0] < 1.0, a0
    assert a0["losses"][-1] < a0["losses"][0]
    # both re-ranked workers completed
    assert (out / "result.rank1.attempt1.json").exists()


@pytest.mark.slow
def test_persistent_slot_failure_scales_in(tmp_path):
    """A slot that dies on every attempt gets dropped: the gang re-forms
    smaller with contiguous re-ranked ids and finishes the job."""
    script = tmp_path / "worker.py"
    # kill_step 0 + attempt checked below: slot 1 dies on attempts 0 AND 1
    script.write_text(_WORKER.replace(
        "if attempt == 0 and rank == 1 and step == kill_step:",
        "if os.environ.get('PADDLE_WORKER_SLOT') == '1' and step >= kill_step:"))
    out = tmp_path / "out"
    out.mkdir()
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "3", "--elastic",
         "--elastic_allow_scale_in", "--ckpt_dir", str(ckpt),
         str(script), str(out), "2"],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "scaling in to 1 workers" in r.stderr
    final = json.load(open(out / "result.rank0.attempt2.json"))
    assert final["world"] == 1          # re-formed smaller world
    assert final["start"] >= 1          # resumed from checkpoint, not 0
    assert final["final_loss"] < 1e-2   # full 10-step trajectory reached


_NODE_WORKER = r'''
import json, os, sys, time
rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
attempt = int(os.environ.get("PADDLE_ELASTIC_ATTEMPT", "0"))
out_dir = sys.argv[1]
with open(os.path.join(out_dir, f"env.rank{rank}.attempt{attempt}.json"),
          "w") as fh:
    json.dump({"world": world, "rank": rank}, fh)
if attempt == 0 and rank == 1:
    # simulate the peer node dying with us: stamp its heartbeat stale
    peer = os.path.join(os.environ["PADDLE_ELASTIC_CKPT_DIR"],
                        ".membership", "node.000001")
    with open(peer, "w") as fh:
        fh.write(str(time.time() - 600))
    sys.exit(3)
'''


@pytest.mark.slow
def test_membership_rerank_shrinks_world_on_node_loss(tmp_path):
    """nnodes=2 where the peer node's heartbeat stops mid-run: after the
    gang failure the supervisor re-ranks over the live membership and
    respawns with the smaller world (reference elastic manager node-loss
    path)."""
    script = tmp_path / "worker.py"
    script.write_text(_NODE_WORKER)
    out = tmp_path / "out"
    out.mkdir()
    ckpt = tmp_path / "ckpt"
    # peer node alive well past attempt 0 (future stamp outlives the
    # launcher's import/startup time); the dying worker stamps it stale
    mdir = ckpt / ".membership"
    mdir.mkdir(parents=True)
    (mdir / "node.000001").write_text(str(time.time() + 600))
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "2", "--node_rank", "0", "--nproc_per_node", "2",
         "--max_restarts", "1", "--elastic", "--ckpt_dir", str(ckpt),
         "--heartbeat_timeout", "5", str(script), str(out)],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    a0 = json.load(open(out / "env.rank0.attempt0.json"))
    assert a0["world"] == 4             # both nodes live at start
    a1 = json.load(open(out / "env.rank0.attempt1.json"))
    assert a1["world"] == 2, a1         # re-ranked over live membership
