"""Unique name generator (reference: python/paddle/utils/unique_name.py →
fluid/unique_name.py)."""
from __future__ import annotations

import contextlib
from collections import defaultdict

_counters = defaultdict(int)
_prefix = []


def generate(key: str) -> str:
    _counters[key] += 1
    base = f"{key}_{_counters[key] - 1}"
    return "/".join(_prefix + [base]) if _prefix else base


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    global _counters
    old = _counters
    _counters = defaultdict(int)
    if new_prefix:
        _prefix.append(new_prefix.rstrip("/"))
    try:
        yield
    finally:
        _counters = old
        if new_prefix:
            _prefix.pop()


def switch(new_generator=None):
    global _counters
    old = _counters
    _counters = new_generator if new_generator is not None else defaultdict(int)
    return old
