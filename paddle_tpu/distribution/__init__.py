"""Probability distributions. Reference: python/paddle/distribution/*."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.random_seed import next_key
from ..tensor import Tensor, apply
from ..tensor_ops._factory import raw


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        from ..tensor_ops.math import exp
        return exp(self.log_prob(value))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(jnp.asarray(float(loc)))
        self.scale = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(float(scale)))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return apply(lambda s: s * s, self.scale)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(
            raw(self.loc).shape, raw(self.scale).shape))
        eps = jax.random.normal(next_key(), shp)
        return Tensor(raw(self.loc) + raw(self.scale) * eps)

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        return apply(lambda v, m, s: -((v - m) ** 2) / (2 * s * s)
                     - jnp.log(s) - 0.5 * math.log(2 * math.pi),
                     value, self.loc, self.scale)

    def entropy(self):
        return apply(lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                     self.scale)

    def kl_divergence(self, other):
        return apply(lambda m1, s1, m2, s2:
                     jnp.log(s2 / s1) + (s1 ** 2 + (m1 - m2) ** 2) / (2 * s2 ** 2) - 0.5,
                     self.loc, self.scale, other.loc, other.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = low if isinstance(low, Tensor) else Tensor(jnp.asarray(float(low)))
        self.high = high if isinstance(high, Tensor) else Tensor(jnp.asarray(float(high)))

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(
            raw(self.low).shape, raw(self.high).shape))
        u = jax.random.uniform(next_key(), shp)
        return Tensor(raw(self.low) + (raw(self.high) - raw(self.low)) * u)

    def log_prob(self, value):
        return apply(lambda v, lo, hi: jnp.where(
            (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf),
            value, self.low, self.high)

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) else Tensor(jnp.asarray(logits))

    def sample(self, shape=()):
        return Tensor(jax.random.categorical(
            next_key(), raw(self.logits), shape=tuple(shape) + raw(self.logits).shape[:-1] if shape else None))

    def log_prob(self, value):
        idx = raw(value).astype(jnp.int32)
        return apply(lambda lg: jnp.take_along_axis(
            jax.nn.log_softmax(lg, -1), idx[..., None], -1)[..., 0], self.logits)

    def probs(self, value):
        idx = raw(value).astype(jnp.int32)
        return apply(lambda lg: jnp.take_along_axis(
            jax.nn.softmax(lg, -1), idx[..., None], -1)[..., 0], self.logits)

    def entropy(self):
        def f(lg):
            p = jax.nn.softmax(lg, -1)
            return -jnp.sum(p * jax.nn.log_softmax(lg, -1), axis=-1)
        return apply(f, self.logits)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = probs if isinstance(probs, Tensor) else Tensor(jnp.asarray(float(probs)))

    def sample(self, shape=()):
        p = raw(self.probs_)
        return Tensor(jax.random.bernoulli(
            next_key(), p, tuple(shape) + p.shape).astype(jnp.float32))

    def log_prob(self, value):
        return apply(lambda v, p: v * jnp.log(jnp.clip(p, 1e-12, None)) +
                     (1 - v) * jnp.log(jnp.clip(1 - p, 1e-12, None)),
                     value, self.probs_)

    def entropy(self):
        return apply(lambda p: -(p * jnp.log(jnp.clip(p, 1e-12, None)) +
                                 (1 - p) * jnp.log(jnp.clip(1 - p, 1e-12, None))),
                     self.probs_)


class Beta(Distribution):
    def __init__(self, alpha, concentration1=None, name=None, beta=None):
        b = beta if beta is not None else concentration1
        self.alpha = alpha if isinstance(alpha, Tensor) else Tensor(jnp.asarray(float(alpha)))
        self.beta = b if isinstance(b, Tensor) else Tensor(jnp.asarray(float(b)))

    def sample(self, shape=()):
        return Tensor(jax.random.beta(next_key(), raw(self.alpha),
                                      raw(self.beta), tuple(shape) or None))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        return apply(lambda v, a, b: (a - 1) * jnp.log(v) +
                     (b - 1) * jnp.log1p(-v) - betaln(a, b),
                     value, self.alpha, self.beta)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = concentration if isinstance(concentration, Tensor) \
            else Tensor(jnp.asarray(concentration))

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(next_key(), raw(self.concentration),
                                           tuple(shape) or ()))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(jnp.asarray(float(loc)))
        self.scale = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(float(scale)))

    def sample(self, shape=()):
        shp = tuple(shape) + raw(self.loc).shape
        return Tensor(raw(self.loc) + raw(self.scale) *
                      jax.random.gumbel(next_key(), shp))


def kl_divergence(p, q):
    if isinstance(p, Normal) and isinstance(q, Normal):
        return p.kl_divergence(q)
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        def f(lp, lq):
            pp = jax.nn.softmax(lp, -1)
            return jnp.sum(pp * (jax.nn.log_softmax(lp, -1) -
                                 jax.nn.log_softmax(lq, -1)), -1)
        return apply(f, p.logits, q.logits)
    raise NotImplementedError(f"kl({type(p).__name__}, {type(q).__name__})")
