"""fluid-style optimizers: `XxxOptimizer(learning_rate, parameter_list,
regularization, grad_clip)` with `.minimize(loss)`.

Reference: python/paddle/fluid/optimizer.py. Thin signature adapters over
the 2.x optimizers (parameter_list -> parameters, regularization ->
weight_decay); `minimize` is inherited (eager backward+step, or deferred
to Executor.run inside a recorded static program).
"""
from __future__ import annotations

from .. import optimizer as _opt
from ..incubate.optimizer import LookAhead, ModelAverage  # noqa: F401
from ..static import ExponentialMovingAverage  # noqa: F401


def _map_kwargs(parameter_list, regularization, grad_clip, kwargs):
    out = dict(kwargs)
    if parameter_list is not None:
        out["parameters"] = parameter_list
    if regularization is not None:
        out["weight_decay"] = regularization
    if grad_clip is not None:
        out["grad_clip"] = grad_clip
    return out


def _fluid_opt(base, extra_map=()):
    extra_map = dict(extra_map)

    class _Opt(base):
        def __init__(self, learning_rate, parameter_list=None,
                     regularization=None, grad_clip=None, name=None,
                     **kwargs):
            for old, new in extra_map.items():
                if old in kwargs:
                    kwargs[new] = kwargs.pop(old)
            super().__init__(
                learning_rate=learning_rate,
                **_map_kwargs(parameter_list, regularization, grad_clip,
                              kwargs))

        def minimize(self, loss, startup_program=None, parameter_list=None,
                     no_grad_set=None):
            """fluid dygraph pattern is `loss.backward();
            opt.minimize(loss)` — minimize only APPLIES existing grads
            (reference fluid/optimizer.py dygraph branch collects
            param._grad_ivar()). Falls back to backward+step when no
            grads are populated yet."""
            from ..static import program as _prog
            if _prog._current_main is not None:
                if self._parameter_list is None:
                    # classic fluid: optimizer without a parameter list
                    # optimizes every parameter of the current program
                    self._parameter_list = list(
                        _prog._current_main.all_parameters())
                return super().minimize(loss, startup_program,
                                        parameter_list, no_grad_set)
            if any(p.grad is not None for p in self._all_params()):
                self.step()
                return None, None
            return super().minimize(loss, startup_program, parameter_list,
                                    no_grad_set)

    _Opt.__name__ = base.__name__ + "Optimizer"
    _Opt.__qualname__ = _Opt.__name__
    return _Opt


SGDOptimizer = _fluid_opt(_opt.SGD)
MomentumOptimizer = _fluid_opt(_opt.Momentum)
AdagradOptimizer = _fluid_opt(_opt.Adagrad)
AdamOptimizer = _fluid_opt(_opt.Adam)
AdamaxOptimizer = _fluid_opt(_opt.Adamax)
AdadeltaOptimizer = _fluid_opt(_opt.Adadelta)
RMSPropOptimizer = _fluid_opt(_opt.RMSProp)
LambOptimizer = _fluid_opt(_opt.Lamb, {"lamb_weight_decay": "lamb_weight_decay"})
LarsMomentumOptimizer = _fluid_opt(_opt.LarsMomentum)
LarsMomentum = LarsMomentumOptimizer
DecayedAdagradOptimizer = AdagradOptimizer
DpsgdOptimizer = SGDOptimizer

# bare aliases (fluid exports both spellings)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer


class RecomputeOptimizer:
    """Wrapper marking checkpoints for recompute (reference
    fluid/optimizer.py:RecomputeOptimizer). Gradient rematerialization is
    jax.checkpoint's job here; the wrapper preserves the API and routes
    minimize to the inner optimizer."""

    def __init__(self, optimizer):
        self._inner = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program, parameter_list,
                                    no_grad_set)


class PipelineOptimizer:
    """API shim (reference fluid/optimizer.py:PipelineOptimizer); real
    pipeline scheduling lives in distributed.fleet (1F1B/GPipe)."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self._inner = optimizer
        self._num_microbatches = num_microbatches

    def __getattr__(self, item):
        return getattr(self._inner, item)


__all__ = ['SGD', 'SGDOptimizer', 'Momentum', 'MomentumOptimizer',
           'Adagrad', 'AdagradOptimizer', 'Adam', 'AdamOptimizer',
           'Adamax', 'AdamaxOptimizer', 'Adadelta', 'AdadeltaOptimizer',
           'RMSProp', 'RMSPropOptimizer', 'Lamb', 'LambOptimizer',
           'LarsMomentumOptimizer', 'DecayedAdagradOptimizer',
           'DpsgdOptimizer', 'RecomputeOptimizer', 'PipelineOptimizer',
           'LookAhead', 'ModelAverage', 'ExponentialMovingAverage']
