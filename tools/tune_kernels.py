#!/usr/bin/env python
"""tune_kernels — search tile configs for the pallas suite and emit a
ledger (paddle_tpu.tuner CLI).

    # offline (cost-model) search of every registered kernel, JSON ledger
    JAX_PLATFORMS=cpu python tools/tune_kernels.py --offline --json

    # one kernel, measured on the live backend, persisted to the AOT store
    PADDLE_TPU_AOT_CACHE_DIR=~/.cache/paddle_tpu_aot \
        python tools/tune_kernels.py --kernel flash_attention

Per kernel the CLI runs the registry's CPU-sized demo shapes through:

1. **parity gate** — the winning config (interpret mode) vs the jnp
   reference, within the registered tolerance; ANY parity failure exits
   non-zero (this is the tier-1 smoke contract);
2. **search** — offline cost-model ranking by default on CPU, measured
   min-of-batches when an accelerator is up (or ``--measured``);
3. **persist** — winner config (+ executable when a persistent AOT
   store is configured) through ``aot.DiskCache``.

The JSON ledger records the elected config, mode, score, space size and
parity verdict per kernel — the artifact the bench arms and the
acceptance test read the tuner's choice from.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run_kernel(name, mode, rng):
    import numpy as np

    from paddle_tpu import tuner
    from paddle_tpu.tuner.registry import get as get_spec
    spec = get_spec(name)
    args, shapes, dtype = spec.demo(rng)
    rec = {"kernel": name, "shapes": shapes, "dtype": dtype}
    result = tuner.tune(name, args=args, mode=mode)
    rec.update(result.to_dict())
    # parity gate at the ELECTED config, interpret mode (the CPU truth)
    try:
        got = np.asarray(spec.build(dict(result.config),
                                    interpret=True)(*args), np.float32)
        ref = np.asarray(spec.reference(*args), np.float32)
        err = float(np.max(np.abs(got - ref)))
        rec["parity"] = {"max_abs_err": err, "tol": spec.tol,
                         "ok": bool(err <= spec.tol)}
    except Exception as e:
        rec["parity"] = {"ok": False,
                         "error": f"{type(e).__name__}: {str(e)[:200]}"}
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tune_kernels",
        description="search-based pallas kernel autotuner "
                    "(paddle_tpu.tuner)")
    ap.add_argument("--kernel", action="append", default=[],
                    help="kernel name (repeatable; default: all)")
    ap.add_argument("--offline", action="store_true",
                    help="force cost-model ranking (no measurement)")
    ap.add_argument("--measured", action="store_true",
                    help="force on-device measurement")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON ledger object")
    ap.add_argument("--out", metavar="FILE",
                    help="also write the ledger JSON to FILE")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.offline and args.measured:
        ap.error("--offline and --measured are mutually exclusive")
    mode = ("offline" if args.offline
            else "measured" if args.measured else "auto")

    import numpy as np

    import jax

    from paddle_tpu import tuner
    from paddle_tpu.aot import get_service

    names = args.kernel or tuner.names()
    rng = np.random.default_rng(args.seed)
    ledger = {"backend": jax.default_backend(), "mode": mode,
              "aot_persistent": get_service().persistent, "kernels": {}}
    ok = True
    for name in names:
        try:
            rec = run_kernel(name, mode, rng)
        except Exception as e:
            rec = {"kernel": name,
                   "error": f"{type(e).__name__}: {str(e)[:200]}",
                   "parity": {"ok": False}}
        ledger["kernels"][name] = rec
        ok = ok and rec.get("parity", {}).get("ok", False)
    ledger["ok"] = ok

    doc = json.dumps(ledger, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc)
    if args.json:
        print(doc)
    else:
        for name, rec in ledger["kernels"].items():
            par = rec.get("parity", {})
            print(f"{name:16s} {rec.get('mode', '?'):8s} "
                  f"config={rec.get('config')} "
                  f"parity={'ok' if par.get('ok') else 'FAIL'}")
        print("OK" if ok else "FAIL: kernel parity")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
