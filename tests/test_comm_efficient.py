"""LocalSGD + DGC (round-2 verdict #7).

Reference: fleet/meta_optimizers/localsgd_optimizer.py:12,
dgc_optimizer.py:1. LocalSGD with k=1 must equal synchronous DP exactly;
k=4 must still converge. DGC at 99% sparsity must converge on a quadratic
and keep parameters replica-identical.
"""
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy

DP = 4


def _mlp():
    paddle_tpu.seed(0)
    return nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 1))


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 8)).astype(np.float32)
    w = rng.standard_normal((8,)).astype(np.float32)
    y = (x @ w + 0.1 * rng.standard_normal(n)).astype(np.float32)[:, None]
    return x, y


def _mse(m, x, y):
    out = m(x)
    return ((out - y) ** 2).mean()


def _run(localsgd=None, dgc=None, steps=12, lr=0.05):
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": DP, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    if localsgd is not None:
        strategy.localsgd = True
        strategy.localsgd_configs = {"k_steps": localsgd}
    if dgc is not None:
        strategy.dgc = True
        strategy.dgc_configs = {"momentum": 0.9, "sparsity": dgc}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(_mlp())
    opt = fleet.distributed_optimizer(
        optim.SGD(learning_rate=lr, parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(model, _mse)
    x, y = _data()
    xt, yt = paddle_tpu.to_tensor(x), paddle_tpu.to_tensor(y)
    losses = [float(np.asarray(step(xt, yt)._data)) for _ in range(steps)]
    return losses, model


def test_localsgd_k1_equals_sync_dp():
    l_sync, m_sync = _run()
    l_k1, m_k1 = _run(localsgd=1)
    np.testing.assert_allclose(l_k1, l_sync, rtol=1e-4, atol=1e-6)
    for (k1, p1), (k2, p2) in zip(sorted(m_sync.named_parameters()),
                                  sorted(m_k1.named_parameters())):
        np.testing.assert_allclose(np.asarray(p2._data),
                                   np.asarray(p1._data), atol=1e-5)


def test_localsgd_k4_converges():
    l_sync, _ = _run(steps=16)
    l_k4, _ = _run(localsgd=4, steps=16)
    assert l_k4[-1] < l_k4[0] * 0.5, l_k4
    # within 2x of the synchronous loss after the same steps
    assert l_k4[-1] < max(l_sync[-1] * 2.0, 0.05), (l_k4[-1], l_sync[-1])


def test_localsgd_replicas_synced_after_avg_step():
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": DP, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    strategy.localsgd = True
    strategy.localsgd_configs = {"k_steps": 3, "begin_step": 0}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(_mlp())
    opt = fleet.distributed_optimizer(
        optim.SGD(learning_rate=0.05, parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(model, _mse)
    x, y = _data()
    xt, yt = paddle_tpu.to_tensor(x), paddle_tpu.to_tensor(y)
    step(xt, yt)  # step 1: replicas diverge
    pv = next(iter(step._param_vals.values()))
    spread = float(np.abs(np.asarray(pv) -
                          np.asarray(pv)[0:1]).max())
    assert spread > 0, "replicas should differ between averages"
    step(xt, yt)
    step(xt, yt)  # step 3: average
    pv = next(iter(step._param_vals.values()))
    spread = float(np.abs(np.asarray(pv) - np.asarray(pv)[0:1]).max())
    assert spread == 0.0, f"replicas not synced after k-th step: {spread}"


def test_dgc_converges_at_99pct_sparsity():
    # momentum correction amplifies the effective step ~1/(1-m); DGC
    # needs the correspondingly smaller lr (same as the reference's
    # rampup guidance)
    losses, model = _run(dgc=0.99, steps=60, lr=0.005)
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_dgc_quadratic_reaches_optimum():
    """Pure quadratic: DGC with momentum correction must reach the
    optimum despite sending only ~1% of gradient entries per step."""
    paddle_tpu.seed(0)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": DP, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    strategy.dgc = True
    # momentum 0 isolates the sparsification dynamics: on a deterministic
    # quadratic the momentum amplification would demand an impractically
    # small lr (it multiplies the released-residual impulse by 1/(1-m))
    strategy.dgc_configs = {"momentum": 0.0, "sparsity": 0.99}
    fleet.init(is_collective=True, strategy=strategy)

    target = np.random.default_rng(1).standard_normal(200).astype(np.float32)

    class Quad(nn.Layer):
        def __init__(self):
            super().__init__()
            self.w = self.create_parameter((200,))

        def forward(self, t):
            return ((self.w - t) ** 2).sum()

    model = fleet.distributed_model(Quad())
    opt = fleet.distributed_optimizer(
        optim.SGD(learning_rate=4e-3, parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(model, lambda m, t: m(t))
    t = paddle_tpu.to_tensor(np.tile(target[None], (DP, 1)))
    losses = [float(np.asarray(step(t)._data)) for _ in range(800)]
    assert losses[-1] < losses[0] * 1e-4, (losses[0], losses[-1])


def test_dgc_momentum_correction_state_shapes():
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": DP, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    strategy.dgc = True
    strategy.dgc_configs = {"momentum": 0.9, "sparsity": 0.99}
    fleet.init(is_collective=True, strategy=strategy)
    paddle_tpu.seed(0)
    model = fleet.distributed_model(_mlp())
    opt = fleet.distributed_optimizer(
        optim.SGD(learning_rate=0.005, parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(model, _mse)
    x, y = _data()
    step(paddle_tpu.to_tensor(x), paddle_tpu.to_tensor(y))
    # residual state is per-replica: [dp, N], N = total param count
    n = sum(int(np.prod(p.shape)) for p in model.parameters())
    assert step._u.shape == (DP, n)
    assert step._v.shape == (DP, n)
    # after the first step some residual must remain unsent (99% sparsity)
    assert float(np.abs(np.asarray(step._v)).sum()) > 0
    for p in model.parameters():
        assert np.isfinite(np.asarray(p._data)).all()


def _run_fp16_allreduce(dtype, steps=12, lr=0.05, opt_cls=None):
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": DP, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    strategy.fp16_allreduce = True
    strategy.fp16_allreduce_configs = {"dtype": dtype}
    fleet.init(is_collective=True, strategy=strategy)
    paddle_tpu.seed(0)
    model = fleet.distributed_model(_mlp())
    opt_cls = opt_cls or optim.SGD
    opt = fleet.distributed_optimizer(
        opt_cls(learning_rate=lr, parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(model, _mse)
    x, y = _data()
    xt, yt = paddle_tpu.to_tensor(x), paddle_tpu.to_tensor(y)
    losses = [float(np.asarray(step(xt, yt)._data)) for _ in range(steps)]
    return losses, model


@pytest.mark.parametrize("dtype", ["bfloat16", "int8"])
def test_compressed_allreduce_tracks_fp32(dtype):
    """bf16/int8-compressed gradient allreduce must track the exact fp32
    DP trajectory within quantization tolerance."""
    l_exact, m_exact = _run()
    l_comp, m_comp = _run_fp16_allreduce(dtype)
    # losses follow the fp32 path (looser for int8's blockwise error)
    tol = 0.02 if dtype == "bfloat16" else 0.15
    np.testing.assert_allclose(l_comp, l_exact, rtol=tol, atol=1e-3)
    assert l_comp[-1] < l_comp[0] * 0.5


def test_compressed_allreduce_adam_supported():
    """Unlike DGC, any optimizer works — grads arrive averaged and
    full-precision at the update."""
    losses, _ = _run_fp16_allreduce("bfloat16", lr=0.01,
                                    opt_cls=optim.Adam)
    assert losses[-1] < losses[0] * 0.7, losses


def test_compressed_allreduce_params_replicated():
    _, model = _run_fp16_allreduce("int8", steps=3)
    for p in model.parameters():
        arr = p._data
        # replicated output sharding: all addressable shards identical
        vals = {bytes(np.asarray(s.data)) for s in arr.addressable_shards}
        assert len(vals) == 1
