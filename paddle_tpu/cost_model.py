"""Cost model.

Reference: python/paddle/cost_model/cost_model.py — estimates per-op /
whole-program cost by profiling the executor. TPU-native design: XLA
already computes an analytical cost model for every compiled executable,
so this asks the compiler (``jax.jit(...).lower().compile()
.cost_analysis()``) instead of timing kernels, and falls back to wall-time
profiling when asked.
"""
from __future__ import annotations

import time


class CostModel:
    def static_cost_data(self):
        """Reference returns op-cost table data used by auto-parallel; the
        XLA path has no static per-op table — costs come per-program from
        cost_analysis()."""
        return {}

    def profile_measure(self, fn, args=(), kwargs=None, device="tpu",
                        fetch_cost_list=("time",), warmup=1, iters=10):
        """Measure a python callable's wall time (compiled path included)."""
        kwargs = kwargs or {}
        import jax
        for _ in range(warmup):
            out = fn(*args, **kwargs)
        if warmup:
            jax.block_until_ready(getattr(out, "_data", out))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args, **kwargs)
        jax.block_until_ready(getattr(out, "_data", out))
        return {"time": (time.perf_counter() - t0) / iters}

    def xla_cost(self, fn, *example_args):
        """Analytical cost of a jittable raw-array function: flops, bytes
        accessed, and optimal seconds estimate from XLA."""
        import jax
        compiled = jax.jit(fn).lower(*example_args).compile()
        analyses = compiled.cost_analysis()
        ca = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
        ca = ca or {}
        return {
            "flops": float(ca.get("flops", -1.0)),
            "bytes_accessed": float(ca.get("bytes accessed", -1.0)),
            "optimal_seconds": float(ca.get("optimal_seconds", -1.0)),
            "raw": dict(ca),
        }
