"""Reference import-path spelling (python/paddle/profiler/
profiler_statistic.py) for the statistic machinery in statistic.py."""
from . import SortedKeys  # noqa: F401
from .statistic import (ProfilerResult, build_summary,  # noqa: F401
                        load_profiler_result)

__all__ = ["SortedKeys", "ProfilerResult", "build_summary",
           "load_profiler_result"]
