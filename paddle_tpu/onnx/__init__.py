"""Model export (paddle.onnx API shape).

Reference: python/paddle/onnx/export.py:21 (delegates to paddle2onnx).
``export`` traces the layer's forward and writes a real ONNX ModelProto
(``path``.onnx) using the in-tree jaxpr->ONNX converter and the bundled
protobuf schema — no external onnx package required. Pass
``format="stablehlo"`` for the XLA-native interchange artifact instead
(serialized via jax.export, loadable with jax.export.deserialize), or
``format="both"`` for both files.

``paddle_tpu.onnx.run(model, {name: array})`` executes an exported model
with the bundled numpy runtime (verification / host-side inference).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..autograd.tape import functional_mode
from ..jit.api import _swap_params
from ..static import InputSpec
from ..tensor import Tensor
from .converter import OnnxExportError, jaxpr_to_onnx  # noqa: F401
from .runtime import load, run  # noqa: F401

__all__ = ["export", "load", "run", "jaxpr_to_onnx", "OnnxExportError"]


def _example_args(input_spec):
    args = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            shape = [1 if s is None or s < 0 else int(s) for s in spec.shape]
            args.append(jnp.zeros(shape, dtype=spec.dtype or "float32"))
        else:
            args.append(jnp.asarray(spec._data if isinstance(spec, Tensor)
                                    else spec))
    return args


def export(layer, path, input_spec=None, opset_version=None, *,
           format="onnx", input_names=None, **kwargs):
    """Export ``layer`` to ``path``.onnx (and/or ``path``.stablehlo).

    Returns the path of the primary artifact written.
    """
    if input_spec is None:
        raise ValueError("input_spec is required for export")
    if format not in ("onnx", "stablehlo", "both"):
        raise ValueError(f"format must be onnx|stablehlo|both, got {format}")

    args = _example_args(input_spec)
    params = dict(layer.named_parameters())
    param_vals = {k: p._data for k, p in params.items()}

    def fn(pv, *xs):
        with functional_mode(), _swap_params(params, pv):
            out = layer(*[Tensor(x) for x in xs])
        return out._data if isinstance(out, Tensor) else out

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    result = None

    if format in ("onnx", "both"):
        # params as a flat leading tuple so leaf order is deterministic
        names = list(param_vals)
        closed = jax.make_jaxpr(
            lambda flat, *xs: fn(dict(zip(names, flat)), *xs))(
                tuple(param_vals.values()), *args)
        in_names = input_names or [
            getattr(s, "name", None) or f"input_{i}"
            for i, s in enumerate(input_spec)]
        model = jaxpr_to_onnx(
            closed, input_names=in_names, param_values=param_vals,
            graph_name=type(layer).__name__,
            opset=13 if opset_version is None else opset_version)
        with open(path + ".onnx", "wb") as f:
            f.write(model.SerializeToString())
        result = path + ".onnx"

    if format in ("stablehlo", "both"):
        exported = jax.export.export(jax.jit(fn))(param_vals, *args)
        with open(path + ".stablehlo", "wb") as f:
            f.write(exported.serialize())
        with open(path + ".mlir", "w") as f:
            f.write(str(exported.mlir_module()))
        if result is None:
            result = path + ".stablehlo"

    return result
