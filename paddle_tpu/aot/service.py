"""The shared compile service: one trace->lower->compile path for every
subsystem, backed by the persistent executable store in cache.py.

Degradation ladder for a signature lookup (strongest first):

1. **in-memory hit** — the program was already built this process;
2. **disk executable hit** — the signature index names a fingerprint
   whose entry deserializes into a loaded executable: *zero* trace,
   *zero* lower, *zero* XLA backend compile;
3. **disk StableHLO hit** — the executable bytes are absent or the
   backend refuses to deserialize them, but the entry carries a
   ``jax.export`` module: skip trace+lower, pay one backend compile;
4. **fingerprint hit after lowering** — the signature was never seen
   but lowering produced a known program (a second key for the same
   fingerprint — recorded as *key instability* for tpu_lint);
5. **full build** — trace, lower, compile, then serialize + persist
   atomically for the next process.

Corrupt/torn entries at any tier read as a miss one tier down — the
service recompiles and overwrites, it never raises for a cache problem.
"""
from __future__ import annotations

import os
import threading

from ..observability import tracing as _tracing
from ..observability.compile_attr import compile_scope as _compile_scope
from ..observability.metrics import Counter
from . import keys as _keys
from .cache import DiskCache

__all__ = ["CompileService", "AotProgram", "get_service", "reset_service",
           "service_enabled"]

CACHE_HITS = Counter(
    "paddle_aot_cache_hits_total",
    "AOT executable-cache hits by originating subsystem and tier",
    labelnames=("origin", "tier"))
CACHE_MISSES = Counter(
    "paddle_aot_cache_misses_total",
    "AOT executable-cache misses (full trace+lower+compile) by origin",
    labelnames=("origin",))

_DEFAULT_MAX_BYTES = int(os.environ.get(
    "PADDLE_TPU_AOT_CACHE_MAX_BYTES", str(2 << 30)))


def _cache_flag_on() -> bool:
    return os.environ.get("PADDLE_TPU_AOT_CACHE", "1").lower() \
        not in ("0", "false", "off")


class AotProgram:
    """Handle for one compiled program signature.

    ``call`` runs the program. Statics (kwargs or ``static_argnums``
    positions) are accepted for interface parity with the live jitted
    callable but dropped when the backing is an AOT ``Compiled`` —
    compiled objects take dynamic operands only, the statics were baked
    at lowering time (and are part of the signature, so a mismatch is a
    different handle).

    ``source`` is the provenance: ``live`` (passthrough, service
    disabled for this lookup), ``compiled`` (full build this process),
    ``memory``, ``disk-exec`` (deserialized executable — no backend
    compile), ``disk-hlo`` (recompiled from cached StableHLO).
    """

    __slots__ = ("name", "sig", "fingerprint", "source", "_compiled",
                 "_jitted", "_static_argnums")

    def __init__(self, name, sig=None, fingerprint=None, source="live",
                 compiled=None, jitted=None, static_argnums=()):
        self.name = name
        self.sig = sig
        self.fingerprint = fingerprint
        self.source = source
        self._compiled = compiled
        self._jitted = jitted
        self._static_argnums = tuple(static_argnums or ())

    def call(self, *args, **kwargs):
        if self._compiled is None:
            return self._jitted(*args, **kwargs)
        if self._static_argnums:
            args = tuple(a for i, a in enumerate(args)
                         if i not in self._static_argnums)
        return self._compiled(*args)

    def __repr__(self):
        return (f"AotProgram({self.name!r}, source={self.source}, "
                f"sig={str(self.sig)[:12]}...)")


class CompileService:
    def __init__(self, cache_dir=None, max_bytes=None, enabled=None):
        if cache_dir is None:
            cache_dir = os.environ.get("PADDLE_TPU_AOT_CACHE_DIR") or None
        self.cache_dir = cache_dir
        flag = _cache_flag_on() if enabled is None else bool(enabled)
        self._flag = flag
        self.disk = None
        if flag and cache_dir:
            try:
                self.disk = DiskCache(
                    cache_dir,
                    max_bytes=(_DEFAULT_MAX_BYTES if max_bytes is None
                               else int(max_bytes)))
            except OSError:
                self.disk = None
        #: read-only secondary stores (e.g. a save_lm artifact's
        #: precompiled program set), consulted after the primary
        self.sources: list = []
        self._mem: dict = {}
        self._mem_cap = max(64, int(os.environ.get(
            "PADDLE_TPU_AOT_MEM_ENTRIES", "4096")))
        self._lock = threading.RLock()
        # fingerprint -> set of sigs that went through a FULL build for
        # it this process; len > 1 means the signature key failed to
        # unify identical programs (tpu_lint aot-key-instability)
        self._built: dict = {}
        self.counters = {"hits": 0, "misses": 0, "mem_hits": 0,
                         "disk_exec_hits": 0, "disk_hlo_hits": 0,
                         "fingerprint_hits": 0, "compiled": 0,
                         "serialized_bytes": 0, "persist_errors": 0,
                         "corrupt_entries": 0}
        # bounded ring of the most recent cache-degradation reasons:
        # every swallowed revive/persist failure records WHY here
        self.last_errors: list = []

    def _note_error(self, where, e):
        self.last_errors.append(f"{where}: {type(e).__name__}: "
                                f"{str(e)[:160]}")
        del self.last_errors[:-16]

    # -- state -------------------------------------------------------------

    @property
    def persistent(self) -> bool:
        return self._flag and (self.disk is not None or bool(self.sources))

    def add_source(self, path, readonly=True):
        """Attach a read-only secondary entry store (artifact dirs)."""
        if not self._flag or not os.path.isdir(os.path.join(path, "objs")):
            return False
        with self._lock:
            if all(s.root != path for s in self.sources):
                self.sources.append(DiskCache(path, readonly=readonly))
        return True

    def _stores(self):
        return ([self.disk] if self.disk is not None else []) + self.sources

    # -- lookup helpers ----------------------------------------------------

    def _load_entry(self, fp, origin, statics_argnums, name, sig):
        """objs entry -> AotProgram via the deserialize/export ladder,
        or None. Never raises."""
        for store in self._stores():
            payload = self.get_payload(store, fp)
            if payload is None:
                continue
            h = self._revive(payload, fp, origin, statics_argnums, name,
                             sig)
            if h is not None:
                return h
        return None

    def get_payload(self, store, fp):
        payload = store.get(fp)
        if payload is None:
            return None
        if payload.get("format") != _keys.FORMAT_VERSION:
            return None
        return payload

    def _revive(self, payload, fp, origin, static_argnums, name, sig):
        exec_bytes = payload.get("exec")
        if exec_bytes is not None:
            try:
                from jax.experimental.serialize_executable import \
                    deserialize_and_load
                compiled = deserialize_and_load(
                    exec_bytes, payload["in_tree"], payload["out_tree"])
                self.counters["disk_exec_hits"] += 1
                CACHE_HITS.labels(origin=origin, tier="exec").inc()
                return AotProgram(name, sig=sig, fingerprint=fp,
                                  source="disk-exec", compiled=compiled,
                                  static_argnums=static_argnums)
            except Exception as e:   # backend refused the executable:
                self.counters["corrupt_entries"] += 1
                self._note_error("deserialize", e)
        export_bytes = payload.get("export")
        if export_bytes is not None:
            try:
                import jax
                from jax import export as jax_export
                exported = jax_export.deserialize(export_bytes)
                with _compile_scope(origin):
                    compiled = jax.jit(exported.call).lower(
                        *exported.in_avals).compile()
                self.counters["disk_hlo_hits"] += 1
                CACHE_HITS.labels(origin=origin, tier="hlo").inc()
                return AotProgram(name, sig=sig, fingerprint=fp,
                                  source="disk-hlo", compiled=compiled,
                                  static_argnums=static_argnums)
            except Exception as e:   # stale/unloadable export module
                self.counters["corrupt_entries"] += 1
                self._note_error("export-revive", e)
        return None

    # -- the main entry point ----------------------------------------------

    def get(self, name, *, args, statics=None, key_parts=None,
            origin=None, jitted=None, jitted_thunk=None,
            static_argnums=()):
        """Resolve one program signature to an :class:`AotProgram`.

        ``args`` are the dynamic call operands (concrete arrays or
        ShapeDtypeStructs — both produce the same key); ``statics`` the
        static kwargs baked into the lowering; ``key_parts`` whatever
        else pins program identity (code tokens, geometry, donation).
        ``jitted`` (or lazy ``jitted_thunk``) supplies the live
        ``jax.jit`` callable for the miss path; with the service
        disabled it is returned as a passthrough handle untouched.
        """
        origin = origin or name
        if not self.persistent:
            if jitted is None:
                jitted = jitted_thunk()
            return AotProgram(name, jitted=jitted, source="live",
                              static_argnums=static_argnums)
        sig = _keys.sig_hash(name, key_parts, _keys.avals_of(args),
                             statics)
        with self._lock:
            h = self._mem.get(sig)
        if h is not None:
            self.counters["mem_hits"] += 1
            self.counters["hits"] += 1
            return h
        with _tracing.span("aot.cache_lookup", cat="aot",
                           program=name, origin=origin):
            h = self._lookup_disk(name, sig, origin, static_argnums)
        if h is None:
            h = self._build(name, sig, args, statics or {}, origin,
                            jitted if jitted is not None else jitted_thunk(),
                            static_argnums)
        else:
            self.counters["hits"] += 1
        with self._lock:
            if len(self._mem) >= self._mem_cap:
                self._mem.clear()
            self._mem[sig] = h
        return h

    def _lookup_disk(self, name, sig, origin, static_argnums):
        for store in self._stores():
            fp = store.get_index(sig)
            if fp is None:
                continue
            h = self._load_entry(fp, origin, static_argnums, name, sig)
            if h is not None:
                return h
        return None

    def _build(self, name, sig, args, statics, origin, jitted,
               static_argnums):
        self.counters["misses"] += 1
        CACHE_MISSES.labels(origin=origin).inc()
        with _compile_scope(origin):
            lowered = jitted.lower(*args, **statics)
            hlo = lowered.as_text()
            fp = _keys.fingerprint(hlo)
            # the program may already be stored under another signature
            h = self._load_entry(fp, origin, static_argnums, name, sig)
            if h is not None:
                self.counters["fingerprint_hits"] += 1
                with self._lock:
                    # a full build (trace+lower paid) that lands on an
                    # existing fingerprint means the signature failed to
                    # unify identical programs — key instability
                    self._built.setdefault(fp, set()).add((name, sig))
                for store in self._stores():
                    store.put_index(sig, fp, {"name": name})
                return h
            compiled = lowered.compile()
        self.counters["compiled"] += 1
        with self._lock:
            sigs = self._built.setdefault(fp, set())
            sigs.add((name, sig))
        self._persist(fp, sig, name, compiled, jitted, args, statics, hlo)
        return AotProgram(name, sig=sig, fingerprint=fp, source="compiled",
                          compiled=compiled, static_argnums=static_argnums)

    def _persist(self, fp, sig, name, compiled, jitted, args, statics,
                 hlo):
        if self.disk is None:
            return
        # host callbacks hold process-local pointers: such a program
        # must never be revived in another process
        if "callback" in hlo:
            return
        payload = {"format": _keys.FORMAT_VERSION, "name": name,
                   "env": _keys.env_fingerprint()}
        try:
            from jax.experimental.serialize_executable import serialize
            exec_bytes, in_tree, out_tree = serialize(compiled)
            payload.update(exec=exec_bytes, in_tree=in_tree,
                           out_tree=out_tree)
        except Exception as e:  # backend without executable serialization
            payload.update(exec=None, in_tree=None, out_tree=None)
            self._note_error("serialize", e)
        try:
            import jax
            from jax import export as jax_export
            specs = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), args)
            payload["export"] = jax_export.export(
                jax.jit(lambda *a: jitted(*a, **statics)))(*specs).serialize()
        except Exception as e:  # not exportable (donation/symbolic dims)
            payload["export"] = None
            self._note_error("export", e)
        if payload["exec"] is None and payload["export"] is None:
            self.counters["persist_errors"] += 1
            return
        try:
            n = self.disk.put(fp, payload)
            if n:
                self.counters["serialized_bytes"] += n
                self.disk.put_index(sig, fp, {"name": name})
            else:
                self.counters["persist_errors"] += 1
        except Exception as e:
            self.counters["persist_errors"] += 1
            self._note_error("persist", e)

    # -- fingerprint-only path (callers that must trace anyway) ------------

    def compile_lowered(self, lowered, name, origin=None):
        """Compile a ``Lowered`` through the store, keyed by program
        fingerprint only (for paths — static segments, to_static — whose
        tracing is structural and must run per process anyway). Returns
        a callable taking the dynamic operands positionally."""
        origin = origin or name
        if not self.persistent:
            with _compile_scope(origin):
                return lowered.compile()
        hlo = lowered.as_text()
        fp = _keys.fingerprint(hlo)
        with _tracing.span("aot.cache_lookup", cat="aot",
                           program=name, origin=origin):
            h = self._load_entry(fp, origin, (), name, fp)
        if h is not None:
            self.counters["hits"] += 1
            return h._compiled
        self.counters["misses"] += 1
        CACHE_MISSES.labels(origin=origin).inc()
        with _compile_scope(origin):
            compiled = lowered.compile()
        self.counters["compiled"] += 1
        with self._lock:
            self._built.setdefault(fp, set()).add((name, fp))
        if self.disk is not None and "callback" not in hlo:
            payload = {"format": _keys.FORMAT_VERSION, "name": name,
                       "env": _keys.env_fingerprint(), "export": None}
            try:
                from jax.experimental.serialize_executable import serialize
                exec_bytes, in_tree, out_tree = serialize(compiled)
                payload.update(exec=exec_bytes, in_tree=in_tree,
                               out_tree=out_tree)
                n = self.disk.put(fp, payload)
                if n:
                    self.counters["serialized_bytes"] += n
                else:
                    self.counters["persist_errors"] += 1
            except Exception as e:
                self.counters["persist_errors"] += 1
                self._note_error("serialize-lowered", e)
        return compiled

    # -- introspection -----------------------------------------------------

    def instability(self):
        """Programs compiled more than once this process under different
        signature keys — the signature failed to unify them, so warm
        starts will recompile where they should restore."""
        with self._lock:
            return [{"fingerprint": fp,
                     "keys": sorted(n for n, _ in sigs),
                     "n_keys": len(sigs)}
                    for fp, sigs in self._built.items() if len(sigs) > 1]

    def disk_stats(self):
        out = []
        if self.disk is not None:
            out.append(self.disk.stats())
        out.extend(s.stats() for s in self.sources)
        return out

    def stats(self) -> dict:
        return {"enabled": self._flag, "persistent": self.persistent,
                "cache_dir": self.cache_dir,
                **self.counters,
                "last_errors": list(self.last_errors),
                "mem_entries": len(self._mem),
                "instability": self.instability(),
                "disk": self.disk_stats()}


_service = None
_service_lock = threading.Lock()


def get_service() -> CompileService:
    global _service
    if _service is None:
        with _service_lock:
            if _service is None:
                _service = CompileService()
    return _service


def reset_service(**kwargs) -> CompileService:
    """Replace the process service (tests; new env knobs)."""
    global _service
    with _service_lock:
        _service = CompileService(**kwargs)
    return _service


def service_enabled() -> bool:
    return get_service().persistent
