"""In-process analog of the PS metric object (reference
distributed/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ...metric import Auc

__all__ = ["Metric", "init_metric", "print_metric", "print_auc"]


class Metric:
    """The ``metric_ptr`` analog: named AUC calculators fed by update()."""

    def __init__(self):
        self._calculators = {}
        self._configs = {}

    def init_metric(self, method, name, label_var, target_var, *args,
                    **kwargs):
        if method not in ("AucCalculator", "MultiTaskAucCalculator",
                          "CmatchRankAucCalculator", "MaskAucCalculator",
                          "WuAucCalculator"):
            raise ValueError(f"unknown metric method {method!r}")
        self._calculators[name] = Auc()
        self._configs[name] = {"method": method, "label": label_var,
                               "target": target_var, **kwargs}

    def update(self, name, preds, labels):
        """Feed one batch: preds [N] probabilities (or [N, 2]), labels."""
        preds = np.asarray(preds)
        if preds.ndim == 1:
            preds = np.stack([1 - preds, preds], axis=1)
        self._calculators[name].update(preds, np.asarray(labels))

    def get_metric(self, name):
        return float(self._calculators[name].accumulate())

    def flush_metric(self, name):
        self._calculators[name].reset()

    def names(self):
        return sorted(self._calculators)


def init_metric(metric_ptr, metric_yaml_path, cmatch_rank_var="",
                mask_var="", uid_var="", phase=-1, cmatch_rank_group="",
                ignore_rank=False, bucket_size=1000000):
    """Parse the monitor yaml and register its calculators (reference
    metrics.py:26). Accepts the reference yaml schema:
    monitors: [{method, name, label, target, phase}, ...]."""
    try:
        import yaml
        with open(metric_yaml_path) as fh:
            content = yaml.safe_load(fh)
    except ImportError:  # tiny fallback parser for the flat schema
        content = _parse_monitors_yaml(metric_yaml_path)
    for runner in content.get("monitors") or []:
        metric_ptr.init_metric(
            runner["method"], runner["name"], runner.get("label", ""),
            runner.get("target", ""), cmatch_rank_var, mask_var, uid_var,
            1 if runner.get("phase") == "JOINING" else 0,
            cmatch_rank_group, ignore_rank, bucket_size)


def _parse_monitors_yaml(path):
    monitors, cur = [], None
    with open(path) as fh:
        for line in fh:
            s = line.strip()
            if s.startswith("- "):
                cur = {}
                monitors.append(cur)
                s = s[2:]
            if cur is not None and ":" in s:
                k, v = s.split(":", 1)
                cur[k.strip()] = v.strip().strip("'\"")
    return {"monitors": monitors}


def print_metric(metric_ptr, name):
    """Reference metrics.py:102."""
    if "@" in name:  # day-level spelling "name@day"
        name = name.split("@", 1)[0]
    out = f"{name}: AUC={metric_ptr.get_metric(name):.6f}"
    print(out)
    return out


def print_auc(metric_ptr, is_day, phase="all"):
    """Reference metrics.py:120: print every registered AUC."""
    outs = [print_metric(metric_ptr, n) for n in metric_ptr.names()]
    return outs
