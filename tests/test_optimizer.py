"""Optimizer convergence micro-problems + scheduler math (SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _fit_quadratic(opt_cls, lr=0.1, steps=60, **kw):
    paddle.seed(0)
    target = np.asarray([3.0, -2.0], dtype=np.float32)
    w = paddle.Parameter(np.zeros(2, dtype=np.float32))
    opt = opt_cls(learning_rate=lr, parameters=[w], **kw)
    for _ in range(steps):
        loss = paddle.sum((w - paddle.to_tensor(target)) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w.numpy(), target


@pytest.mark.parametrize("cls,lr", [
    (optimizer.SGD, 0.1), (optimizer.Momentum, 0.05),
    (optimizer.Adam, 0.2), (optimizer.AdamW, 0.2),
    (optimizer.RMSProp, 0.05), (optimizer.Adamax, 0.3),
    (optimizer.Adagrad, 0.9), (optimizer.Adadelta, 30.0),
])
def test_converges(cls, lr):
    w, target = _fit_quadratic(cls, lr=lr, steps=120)
    np.testing.assert_allclose(w, target, atol=0.3)


def test_lamb_converges():
    w, target = _fit_quadratic(optimizer.Lamb, lr=0.3, steps=200,
                               lamb_weight_decay=0.0)
    np.testing.assert_allclose(w, target, atol=0.3)


def test_lars_momentum_converges():
    """LarsMomentum (reference fluid/optimizer.py:1975): trust-ratio
    scaled momentum must still reach the quadratic-bowl optimum."""
    w, target = _fit_quadratic(optimizer.LarsMomentum, lr=2.0, steps=400,
                               lars_weight_decay=0.0)
    np.testing.assert_allclose(w, target, atol=0.3)


def test_fleet_strategy_lars_asp_routing():
    """strategy.lars swaps the optimizer for LarsMomentum and
    strategy.asp decorates it with the n:m mask pass (reference
    meta_optimizers/{lars,asp}_optimizer.py routing)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.incubate import asp

    paddle.seed(0)
    model = nn.Linear(8, 8)
    asp.prune_model(model)
    mask_density = np.mean(model.weight.numpy() != 0)
    assert abs(mask_density - 0.5) < 0.05

    strategy = DistributedStrategy()
    strategy.lars = True
    strategy.lars_configs = {"lars_coeff": 0.002,
                             "lars_weight_decay": 0.0}
    strategy.asp = True
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(
        optimizer.Momentum(learning_rate=0.1, momentum=0.8,
                           parameters=model.parameters()),
        strategy=strategy)
    inner = opt._inner._inner  # ASP decorator wraps the swapped Lars
    assert type(inner).__name__ == "LarsMomentum"
    assert inner._lars_coeff == 0.002
    assert inner._momentum == 0.8  # carried from the wrapped Momentum

    x = paddle.to_tensor(np.random.default_rng(0).normal(
        size=(4, 8)).astype(np.float32))
    for _ in range(3):
        loss = (model(x) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    # the eager step re-applies masks: sparsity pattern survives updates
    assert abs(np.mean(model.weight.numpy() != 0) - mask_density) < 1e-6


def test_fleet_strategy_lamb_routing():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy

    model = nn.Linear(4, 4)
    strategy = DistributedStrategy()
    strategy.lamb = True
    strategy.lamb_configs = {"lamb_weight_decay": 0.05}
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(
        optimizer.Adam(learning_rate=3e-4,
                       parameters=model.parameters()),
        strategy=strategy)
    assert type(opt._inner).__name__ == "Lamb"
    assert opt._inner._lamb_wd == 0.05
    assert opt._inner._learning_rate == 3e-4


def test_asp_masks_survive_compiled_train_step():
    """strategy.asp on the compiled path: after make_train_step updates,
    the n:m zeros are still zero (fleet._ASPMaskedStep)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.incubate import asp

    paddle.seed(0)
    model = nn.Linear(8, 4)
    masks = asp.prune_model(model)
    assert masks
    strategy = DistributedStrategy()
    strategy.asp = True
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(
        optimizer.AdamW(learning_rate=1e-2,
                        parameters=model.parameters()),
        strategy=strategy)
    step = opt.make_train_step(model, lambda m, x: (m(x) ** 2).sum())
    x = paddle.to_tensor(np.random.default_rng(0).normal(
        size=(4, 8)).astype(np.float32))
    for _ in range(2):
        loss = step(x)
    assert np.isfinite(float(np.asarray(loss._data)))
    w = model.weight.numpy()
    assert abs(np.mean(w != 0) - 0.5) < 0.05
    # the masked positions are exactly the pruned ones
    mask = list(masks.values())[0]
    assert np.all(w[~np.asarray(mask)] == 0)


def test_adam_matches_torch():
    torch = pytest.importorskip("torch")
    w0 = np.random.default_rng(0).normal(size=(3,)).astype(np.float32)
    g = np.random.default_rng(1).normal(size=(3,)).astype(np.float32)

    p = paddle.Parameter(w0.copy())
    opt = optimizer.Adam(learning_rate=0.1, parameters=[p])
    tp = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    topt = torch.optim.Adam([tp], lr=0.1, eps=1e-8)
    for _ in range(5):
        p.grad = paddle.to_tensor(g)
        opt.step()
        tp.grad = torch.from_numpy(g.copy())
        topt.step()
    np.testing.assert_allclose(p.numpy(), tp.detach().numpy(), rtol=1e-4,
                               atol=1e-5)


def test_adamw_decoupled_decay():
    w0 = np.ones(2, dtype=np.float32)
    p = paddle.Parameter(w0.copy())
    opt = optimizer.AdamW(learning_rate=0.0, parameters=[p], weight_decay=0.1)
    p.grad = paddle.to_tensor(np.zeros(2, dtype=np.float32))
    opt.step()
    # lr=0 → update is -lr*decay*w = 0; decay scales with lr (true AdamW)
    np.testing.assert_allclose(p.numpy(), w0)


def test_weight_decay_coupled_sgd():
    p = paddle.Parameter(np.ones(1, dtype=np.float32))
    opt = optimizer.SGD(learning_rate=0.1, parameters=[p], weight_decay=0.5)
    p.grad = paddle.to_tensor(np.zeros(1, dtype=np.float32))
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.5], rtol=1e-6)


def test_grad_clip_in_optimizer():
    p = paddle.Parameter(np.zeros(2, dtype=np.float32))
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p],
                        grad_clip=nn.ClipGradByGlobalNorm(1.0))
    p.grad = paddle.to_tensor(np.asarray([30.0, 40.0], dtype=np.float32))
    opt.step()
    np.testing.assert_allclose(np.linalg.norm(p.numpy()), 1.0, rtol=1e-4)


def test_lr_scheduler_with_optimizer():
    sched = optimizer.lr.StepDecay(learning_rate=1.0, step_size=2, gamma=0.1)
    opt = optimizer.SGD(learning_rate=sched,
                        parameters=[paddle.Parameter(np.zeros(1, np.float32))])
    assert opt.get_lr() == 1.0
    sched.step()
    sched.step()
    assert abs(opt.get_lr() - 0.1) < 1e-9


def test_schedulers_shapes():
    lr = optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
    vals = []
    for _ in range(10):
        vals.append(lr())
        lr.step()
    assert vals[0] == pytest.approx(1.0)
    assert vals[-1] < 0.1

    warm = optimizer.lr.LinearWarmup(0.5, warmup_steps=5, start_lr=0.0,
                                     end_lr=0.5)
    v0 = warm()
    for _ in range(5):
        warm.step()
    assert v0 == pytest.approx(0.0)
    assert warm() == pytest.approx(0.5)

    noam = optimizer.lr.NoamDecay(d_model=512, warmup_steps=10)
    seq = []
    for _ in range(20):
        seq.append(noam())
        noam.step()
    assert np.argmax(seq) in (9, 10, 11)


def test_optimizer_state_dict_roundtrip():
    p = paddle.Parameter(np.ones(2, dtype=np.float32), name="w")
    opt = optimizer.Adam(learning_rate=0.1, parameters=[p])
    p.grad = paddle.to_tensor(np.ones(2, dtype=np.float32))
    opt.step()
    st = opt.state_dict()
    p2 = paddle.Parameter(p.numpy().copy(), name="w")
    opt2 = optimizer.Adam(learning_rate=0.1, parameters=[p2])
    opt2.set_state_dict(st)
    p.grad = paddle.to_tensor(np.ones(2, dtype=np.float32))
    p2.grad = paddle.to_tensor(np.ones(2, dtype=np.float32))
    opt.step()
    opt2.step()
    np.testing.assert_allclose(p.numpy(), p2.numpy(), rtol=1e-6)


def test_minimize():
    p = paddle.Parameter(np.asarray([5.0], dtype=np.float32))
    opt = optimizer.SGD(learning_rate=0.5, parameters=[p])
    loss = paddle.sum(p * p)
    opt.minimize(loss)
    np.testing.assert_allclose(p.numpy(), [0.0], atol=1e-6)
