"""paddle.dataset.common download/cache machinery.

Reference: python/paddle/dataset/common.py — DATA_HOME, md5-verified
download with retries; mirror/local-file sources for air-gapped envs.
"""
import gzip
import os

import numpy as np
import pytest

from paddle_tpu.dataset import common


def test_download_local_file_and_cache(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    src = tmp_path / "blob.bin"
    src.write_bytes(b"hello dataset")
    md5 = common.md5file(str(src))
    p1 = common.download(str(src), "unit", md5)
    assert os.path.exists(p1)
    assert open(p1, "rb").read() == b"hello dataset"
    # second call hits the cache (delete the source to prove it)
    src.unlink()
    p2 = common.download(str(tmp_path / "blob.bin"), "unit", md5)
    assert p2 == p1


def test_download_md5_mismatch_raises(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    src = tmp_path / "blob2.bin"
    src.write_bytes(b"payload")
    with pytest.raises(RuntimeError, match="md5|failed"):
        common.download(str(src), "unit", "0" * 32, retries=1)


def test_mirror_env_rewrites(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    mirror = tmp_path / "mirror"
    mirror.mkdir()
    (mirror / "archive.gz").write_bytes(b"mirrored")
    monkeypatch.setenv("PADDLE_TPU_DATASET_MIRROR", str(mirror))
    p = common.download("https://unreachable.example/data/archive.gz",
                        "unit", None)
    assert open(p, "rb").read() == b"mirrored"


def test_mnist_download_path_via_mirror(tmp_path, monkeypatch):
    """MNIST(download=True) consumes the download machinery when a mirror
    provides real idx files."""
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    mirror = tmp_path / "mirror"
    mirror.mkdir()
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (7, 28, 28), dtype=np.uint8)
    lbls = rng.integers(0, 10, (7,), dtype=np.uint8)
    with gzip.open(mirror / "train-images-idx3-ubyte.gz", "wb") as f:
        f.write(b"\x00" * 16 + imgs.tobytes())
    with gzip.open(mirror / "train-labels-idx1-ubyte.gz", "wb") as f:
        f.write(b"\x00" * 8 + lbls.tobytes())
    monkeypatch.setenv("PADDLE_TPU_DATASET_MIRROR", str(mirror))

    from paddle_tpu.vision.datasets import MNIST

    class NoMd5MNIST(MNIST):
        FILES = {k: ((v[0][0], None), (v[1][0], None))
                 for k, v in MNIST.FILES.items()}

    ds = NoMd5MNIST(mode="train")
    assert len(ds) == 7
    np.testing.assert_array_equal(ds.images[3], imgs[3])
    _, lab = ds[3]
    assert int(lab) == int(lbls[3])
