// Native byte-pair-encoding tokenizer for the text data pipeline.
//
// TPU-side analog of the reference stack's native tokenization (the
// paddle ecosystem ships faster_tokenizer as a C++ library): python
// calls enter through ctypes (GIL released), so DataLoader workers and
// the prefetch ring can tokenize truly in parallel with model compute.
//
// Semantics mirror paddle_tpu/text/tokenizer.py::BpeTokenizer exactly:
// split text on ' ', greedy lowest-rank pair merge per token over
// UTF-8 codepoints, vocabulary lookup per merged piece (unknown pieces
// dropped). Parity is pinned by tests/test_native_bpe.py.
//
// Build: make -C paddle_tpu/runtime/cpp  (builds libptpu_bpe.so)

#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
  size_t operator()(const std::pair<std::string, std::string>& p) const {
    std::hash<std::string> h;
    return h(p.first) * 1000003u ^ h(p.second);
  }
};

struct Bpe {
  std::unordered_map<std::string, int> vocab;
  std::unordered_map<std::pair<std::string, std::string>, long, PairHash>
      ranks;
  // concurrent encode calls share the handle (ctypes releases the
  // GIL), so the memo cache takes a reader/writer lock
  std::shared_mutex cache_mu;
  std::unordered_map<std::string, std::vector<int>> cache;
};

// split a UTF-8 string into codepoint-sized chunks (python tuple(token))
std::vector<std::string> utf8_chars(const std::string& s) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < s.size()) {
    unsigned char c = s[i];
    size_t n = (c < 0x80) ? 1 : (c >> 5) == 0x6 ? 2
               : (c >> 4) == 0xE ? 3 : (c >> 3) == 0x1E ? 4 : 1;
    if (i + n > s.size()) n = 1;
    out.emplace_back(s.substr(i, n));
    i += n;
  }
  return out;
}

void bpe_token(Bpe* h, const std::string& tok, std::vector<int>* ids) {
  {
    std::shared_lock<std::shared_mutex> lk(h->cache_mu);
    auto it = h->cache.find(tok);
    if (it != h->cache.end()) {
      ids->insert(ids->end(), it->second.begin(), it->second.end());
      return;
    }
  }
  std::vector<std::string> word = utf8_chars(tok);
  while (word.size() > 1) {
    long best_rank = -1;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < word.size(); ++i) {
      auto r = h->ranks.find({word[i], word[i + 1]});
      if (r != h->ranks.end() &&
          (best_rank < 0 || r->second < best_rank)) {
        best_rank = r->second;
        best_i = i;
      }
    }
    if (best_rank < 0) break;
    // merge every occurrence of the best pair (python semantics)
    const std::string a = word[best_i], b = word[best_i + 1];
    std::vector<std::string> merged;
    merged.reserve(word.size());
    for (size_t i = 0; i < word.size();) {
      if (i + 1 < word.size() && word[i] == a && word[i + 1] == b) {
        merged.emplace_back(a + b);
        i += 2;
      } else {
        merged.emplace_back(word[i]);
        i += 1;
      }
    }
    word.swap(merged);
  }
  std::vector<int> toks;
  for (const auto& piece : word) {
    auto v = h->vocab.find(piece);
    if (v != h->vocab.end()) toks.push_back(v->second);
  }
  {
    std::unique_lock<std::shared_mutex> lk(h->cache_mu);
    h->cache.emplace(tok, toks);
  }
  ids->insert(ids->end(), toks.begin(), toks.end());
}

void encode_text(Bpe* h, const char* text, long len,
                 std::vector<int>* ids) {
  const std::string s(text, (size_t)len);
  size_t start = 0;
  while (start <= s.size()) {
    size_t sp = s.find(' ', start);
    size_t end = (sp == std::string::npos) ? s.size() : sp;
    if (end > start) bpe_token(h, s.substr(start, end - start), ids);
    if (sp == std::string::npos) break;
    start = sp + 1;
  }
}

}  // namespace

extern "C" {

// vocab_buf: '\n'-separated token strings, id = line index.
// merges_buf: '\n'-separated "first second" lines, rank = line index.
void* ptpu_bpe_create(const char* vocab_buf, long vocab_len,
                      const char* merges_buf, long merges_len) {
  auto* h = new Bpe();
  {
    const std::string v(vocab_buf, (size_t)vocab_len);
    size_t start = 0;
    int id = 0;
    while (start <= v.size()) {
      size_t nl = v.find('\n', start);
      size_t end = (nl == std::string::npos) ? v.size() : nl;
      if (end > start) h->vocab.emplace(v.substr(start, end - start), id);
      ++id;
      if (nl == std::string::npos) break;
      start = nl + 1;
    }
  }
  {
    const std::string m(merges_buf, (size_t)merges_len);
    size_t start = 0;
    long rank = 0;
    while (start <= m.size()) {
      size_t nl = m.find('\n', start);
      size_t end = (nl == std::string::npos) ? m.size() : nl;
      if (end > start && m[start] != '#') {  // python skips '#' lines
        const std::string line = m.substr(start, end - start);
        size_t sp = line.find(' ');
        if (sp != std::string::npos) {
          h->ranks.emplace(
              std::make_pair(line.substr(0, sp), line.substr(sp + 1)),
              rank);
        }
        ++rank;  // rank counts accepted merge lines only
      }
      if (nl == std::string::npos) break;
      start = nl + 1;
    }
  }
  return h;
}

void ptpu_bpe_destroy(void* handle) { delete static_cast<Bpe*>(handle); }

// encode one string; returns the id count (truncated to max_out).
long ptpu_bpe_encode(void* handle, const char* text, long text_len,
                     int* out, long max_out) {
  std::vector<int> ids;
  encode_text(static_cast<Bpe*>(handle), text, text_len, &ids);
  long n = (long)ids.size() < max_out ? (long)ids.size() : max_out;
  if (n > 0) std::memcpy(out, ids.data(), (size_t)n * sizeof(int));
  return (long)ids.size();
}

// encode n strings packed in `texts` with byte offsets[n+1]; writes ids
// packed into `out` (capacity max_out) with per-string counts in
// `counts[n]`. Returns total ids written (or the required capacity if
// larger than max_out — caller re-invokes with a bigger buffer).
long ptpu_bpe_encode_batch(void* handle, const char* texts,
                           const long* offsets, long n, int* out,
                           long max_out, long* counts) {
  auto* h = static_cast<Bpe*>(handle);
  long total = 0;
  for (long i = 0; i < n; ++i) {
    std::vector<int> ids;
    encode_text(h, texts + offsets[i], offsets[i + 1] - offsets[i],
                &ids);
    counts[i] = (long)ids.size();
    if (total + (long)ids.size() <= max_out) {
      std::memcpy(out + total, ids.data(),
                  ids.size() * sizeof(int));
    }
    total += (long)ids.size();
  }
  return total;
}

}  // extern "C"
