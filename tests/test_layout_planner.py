"""Channels-last layout planner (framework/layout.py): per-op NHWC/NCHW
parity, to_channels_last end-to-end parity, conv+BN folding, the
depthwise fast path, the HLO transpose lint, and plan inheritance by
jit.to_static traces.

Budget note: tier-1 runs close to its wall-clock cap, so the resnet18
pair is built once per module and the heavyweight zoo variants
(mobilenet end-to-end) are marked slow; tools/check_hlo_layout.py and
tools/bench_conv.py carry the full-size evidence.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import (
    ChannelsLast, count_hlo_transposes, fold_conv_bn, to_channels_last,
)

RNG = np.random.default_rng(0)


def t(shape, scale=1.0):
    return paddle.to_tensor(
        (RNG.standard_normal(shape) * scale).astype(np.float32))


def to_nhwc(x):
    return paddle.transpose(x, [0, 2, 3, 1])


def back(x):
    return np.asarray(paddle.transpose(x, [0, 3, 1, 2])._data)


@pytest.fixture(scope="module")
def resnet_pair():
    """(nchw_model, channels_last_wrapper) sharing one weight set.

    Read-only for most tests; the fold test (defined last in file
    order, which tier-1's -p no:randomly preserves) mutates weights
    after capturing its own before-output."""
    from paddle_tpu.vision.models import resnet18
    paddle.seed(1)
    m = resnet18(num_classes=10)
    m.eval()
    paddle.seed(1)
    m2 = resnet18(num_classes=10)
    m2.eval()
    m2.set_state_dict(m.state_dict())
    return m, to_channels_last(m2)


class TestPerOpParity:
    """Every layout-aware functional must produce identical values in
    both layouts (same dimension-numbers conv, no transposes)."""

    def setup_method(self, _):
        paddle.seed(0)
        self.x = t((2, 8, 10, 10))
        self.xn = to_nhwc(self.x)

    def test_conv2d(self):
        w, b = t((16, 8, 3, 3)), t((16,))
        ref = np.asarray(F.conv2d(self.x, w, b, stride=2, padding=1)._data)
        out = F.conv2d(self.xn, w, b, stride=2, padding=1,
                       data_format="NHWC")
        np.testing.assert_array_equal(back(out), ref)

    def test_conv2d_strings_and_dilation(self):
        w = t((16, 8, 3, 3))
        for pad in ("SAME", "VALID"):
            ref = np.asarray(F.conv2d(self.x, w, padding=pad, dilation=2)._data)
            out = F.conv2d(self.xn, w, padding=pad, dilation=2,
                           data_format="NHWC")
            np.testing.assert_array_equal(back(out), ref)

    def test_conv2d_full_form_padding_layout(self):
        """The full-rank padding spelling places spatial entries per the
        layout: [..., [ph,ph], [pw,pw]] NCHW vs [..., spatial ..., [0,0]]
        NHWC."""
        w = t((16, 8, 3, 3))
        ref = np.asarray(F.conv2d(
            self.x, w, padding=[[0, 0], [0, 0], [1, 2], [3, 4]])._data)
        out = F.conv2d(self.xn, w,
                       padding=[[0, 0], [1, 2], [3, 4], [0, 0]],
                       data_format="NHWC")
        np.testing.assert_array_equal(back(out), ref)

    def test_depthwise_fast_path(self):
        w = t((8, 1, 3, 3))
        ref = np.asarray(F.conv2d(self.x, w, padding=1, groups=8)._data)
        out = F.conv2d(self.xn, w, padding=1, groups=8, data_format="NHWC")
        np.testing.assert_array_equal(back(out), ref)
        # depthwise-expanding (out = k * in) and grouped variants
        w2 = t((16, 1, 3, 3))
        ref2 = np.asarray(F.conv2d(self.x, w2, padding=1, groups=8)._data)
        out2 = F.conv2d(self.xn, w2, padding=1, groups=8, data_format="NHWC")
        np.testing.assert_array_equal(back(out2), ref2)
        w3 = t((12, 2, 3, 3))
        ref3 = np.asarray(F.conv2d(self.x, w3, padding=1, groups=4)._data)
        out3 = F.conv2d(self.xn, w3, padding=1, groups=4, data_format="NHWC")
        np.testing.assert_array_equal(back(out3), ref3)

    def test_depthwise_emits_no_transposes(self):
        """The NHWC depthwise path keeps the OIHW weight spec: no
        transpose ops in the emitted HLO (the fast-path contract)."""
        paddle.seed(0)
        conv = nn.Conv2D(8, 8, 3, padding=1, groups=8, data_format="NHWC")
        xn = paddle.to_tensor(np.asarray(self.xn._data))
        assert count_hlo_transposes(conv, xn) == 0

    def test_conv2d_transpose(self):
        w = t((8, 4, 3, 3))
        ref = np.asarray(F.conv2d_transpose(
            self.x, w, stride=2, padding=1, output_padding=1)._data)
        out = F.conv2d_transpose(self.xn, w, stride=2, padding=1,
                                 output_padding=1, data_format="NHWC")
        np.testing.assert_array_equal(back(out), ref)

    def test_grouped_conv2d_transpose(self):
        w, b = t((8, 2, 3, 3)), t((4,))
        ref = np.asarray(F.conv2d_transpose(
            self.x, w, b, stride=2, groups=2)._data)
        out = F.conv2d_transpose(self.xn, w, b, stride=2, groups=2,
                                 data_format="NHWC")
        np.testing.assert_array_equal(back(out), ref)

    def test_batch_norm_eval_and_train(self):
        rm1, rv1 = t((8,)), paddle.to_tensor(
            (np.abs(RNG.standard_normal(8)) + 0.5).astype(np.float32))
        rm2 = paddle.to_tensor(np.asarray(rm1._data).copy())
        rv2 = paddle.to_tensor(np.asarray(rv1._data).copy())
        g, b = t((8,)), t((8,))
        ref = np.asarray(F.batch_norm(self.x, rm1, rv1, g, b,
                                      training=False)._data)
        out = F.batch_norm(self.xn, rm2, rv2, g, b, training=False,
                           data_format="NHWC")
        np.testing.assert_allclose(back(out), ref, rtol=1e-6, atol=1e-6)
        # training mode: normalized output AND running-stat updates match
        ref = np.asarray(F.batch_norm(self.x, rm1, rv1, g, b,
                                      training=True)._data)
        out = F.batch_norm(self.xn, rm2, rv2, g, b, training=True,
                           data_format="NHWC")
        np.testing.assert_allclose(back(out), ref, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(rm2._data),
                                   np.asarray(rm1._data), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(rv2._data),
                                   np.asarray(rv1._data), rtol=1e-6)

    def test_pools(self):
        for ref_t, out_t in (
            (F.max_pool2d(self.x, 3, stride=2, padding=1),
             F.max_pool2d(self.xn, 3, stride=2, padding=1,
                          data_format="NHWC")),
            (F.avg_pool2d(self.x, 2, stride=2, exclusive=False),
             F.avg_pool2d(self.xn, 2, stride=2, exclusive=False,
                          data_format="NHWC")),
            (F.avg_pool2d(self.x, 3, stride=1, padding=1),
             F.avg_pool2d(self.xn, 3, stride=1, padding=1,
                          data_format="NHWC")),
            (F.adaptive_avg_pool2d(self.x, (5, 5)),
             F.adaptive_avg_pool2d(self.xn, (5, 5), data_format="NHWC")),
            (F.adaptive_avg_pool2d(self.x, (3, 3)),  # uneven bins
             F.adaptive_avg_pool2d(self.xn, (3, 3), data_format="NHWC")),
            (F.adaptive_avg_pool2d(self.x, (1, 1)),
             F.adaptive_avg_pool2d(self.xn, (1, 1), data_format="NHWC")),
        ):
            np.testing.assert_allclose(back(out_t), np.asarray(ref_t._data),
                                       rtol=1e-6, atol=1e-6)

    def test_conv_grad_parity(self):
        """Gradients flow through the NHWC dimension-numbers conv
        identically to the NCHW one."""
        w1 = t((6, 8, 3, 3))
        w2 = paddle.to_tensor(np.asarray(w1._data).copy())
        w1.stop_gradient = False
        w2.stop_gradient = False
        F.conv2d(self.x, w1, padding=1).sum().backward()
        F.conv2d(self.xn, w2, padding=1, data_format="NHWC").sum().backward()
        np.testing.assert_allclose(np.asarray(w2.grad._data),
                                   np.asarray(w1.grad._data),
                                   rtol=1e-5, atol=1e-5)


def _safe_stack():
    """A small layout-safe conv chain (cheap stand-in for the zoo)."""
    paddle.seed(2)
    return nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1, bias_attr=False),
        nn.BatchNorm2D(8),
        nn.ReLU(),
        nn.MaxPool2D(2),
        nn.Conv2D(8, 8, 3, padding=1, groups=8),
        nn.AvgPool2D(2),
    )


class TestToChannelsLast:
    def test_resnet18_end_to_end_parity(self, resnet_pair):
        m, cl = resnet_pair
        x = t((2, 3, 32, 32))
        ref = np.asarray(m(x)._data)
        assert isinstance(cl, ChannelsLast)
        assert len(cl.plan.converted) >= 40  # 20 convs + 20 BNs + pools
        np.testing.assert_array_equal(np.asarray(cl(x)._data), ref)

    def test_4d_output_transposed_back(self):
        """A region whose output is 4D gets the exit boundary transpose
        — output returns in NCHW."""
        stack = _safe_stack()
        stack.eval()
        x = t((1, 3, 8, 8))
        ref = np.asarray(stack(x)._data)
        out = np.asarray(to_channels_last(stack, force=True)(x)._data)
        assert out.shape == ref.shape  # NCHW restored
        np.testing.assert_array_equal(out, ref)

    def test_unsafe_model_requires_force(self):
        class Odd(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 4, 3)

            def forward(self, x):
                return self.conv(x)

        with pytest.raises(ValueError, match="channels-last-safe"):
            to_channels_last(Odd())

    def test_idempotent(self, resnet_pair):
        _, cl = resnet_pair
        assert to_channels_last(cl) is cl

    def test_zoo_opt_in_markers(self):
        from paddle_tpu.vision.models.mobilenet import (
            MobileNetV1, MobileNetV2, MobileNetV3,
        )
        from paddle_tpu.vision.models.resnet import ResNet
        for cls in (ResNet, MobileNetV1, MobileNetV2, MobileNetV3):
            assert cls._channels_last_safe is True

    @pytest.mark.slow
    def test_mobilenet_end_to_end(self):
        from paddle_tpu.vision.models import mobilenet_v2
        paddle.seed(3)
        m = mobilenet_v2(num_classes=10)
        m.eval()
        paddle.seed(3)
        m2 = mobilenet_v2(num_classes=10)
        m2.eval()
        m2.set_state_dict(m.state_dict())
        x = t((1, 3, 32, 32))
        ref = np.asarray(m(x)._data)
        np.testing.assert_array_equal(
            np.asarray(to_channels_last(m2)(x)._data), ref)


class TestFoldConvBN:
    def test_single_pair_parity(self):
        """The fp32 <=1e-5 folding contract on one conv+BN pair."""
        paddle.seed(3)
        conv = nn.Conv2D(8, 16, 3, padding=1, bias_attr=False)
        bn = nn.BatchNorm2D(16)
        bn._mean._data = t((16,))._data
        bn._variance._data = paddle.to_tensor(
            (np.abs(RNG.standard_normal(16)) + 0.3).astype(np.float32))._data
        bn.weight._data = t((16,))._data
        bn.bias._data = t((16,))._data
        seq = nn.Sequential(conv, bn)
        seq.eval()
        x = t((2, 8, 12, 12))
        before = np.asarray(seq(x)._data)
        folded = fold_conv_bn(seq)
        assert folded == ["1"]
        from paddle_tpu.nn.layer.common import Identity
        assert isinstance(seq._sub_layers["1"], Identity)
        assert conv.bias is not None  # bias materialized by the fold
        after = np.asarray(seq(x)._data)
        assert np.abs(after - before).max() <= 1e-5

    def test_conv_with_bias_folds_in_place(self):
        paddle.seed(4)
        conv = nn.Conv2D(4, 8, 3, padding=1)  # has a bias already
        bn = nn.BatchNorm2D(8)
        bn._mean._data = t((8,))._data
        seq = nn.Sequential(conv, bn)
        seq.eval()
        x = t((1, 4, 9, 9))
        before = np.asarray(seq(x)._data)
        assert fold_conv_bn(seq) == ["1"]
        np.testing.assert_allclose(np.asarray(seq(x)._data), before,
                                   rtol=1e-5, atol=1e-5)

    def test_relu_not_folded(self):
        """conv -> relu -> bn must NOT fold (not adjacent dataflow)."""
        paddle.seed(5)
        seq = nn.Sequential(nn.Conv2D(4, 8, 3), nn.ReLU(), nn.BatchNorm2D(8))
        seq.eval()
        assert fold_conv_bn(seq) == []


class TestHLOLayout:
    def test_resnet18_zero_interior_transposes(self, resnet_pair):
        """The tentpole claim: the channels-last jitted forward emits no
        layout transposes except the entry boundary."""
        _, cl = resnet_pair
        x = t((1, 3, 32, 32))
        xn = to_nhwc(x)
        assert count_hlo_transposes(cl.model, xn) == 0
        assert count_hlo_transposes(cl, x) <= 1

    def test_small_stack_zero_transposes(self):
        paddle.seed(0)
        stack = nn.Sequential(
            nn.Conv2D(3, 8, 3, padding=1, data_format="NHWC"),
            nn.BatchNorm2D(8, data_format="NHWC"),
            nn.ReLU(),
            nn.MaxPool2D(2, data_format="NHWC"),
            nn.AdaptiveAvgPool2D((1, 1), data_format="NHWC"),
        )
        stack.eval()
        xn = t((1, 6, 6, 3))
        assert count_hlo_transposes(stack, xn) == 0


class TestPlanInheritance:
    def test_static_executor_inherits_layout(self):
        """The record/replay Executor replays whatever the converted
        layers emit — the layout plan needs no Program plumbing."""
        from paddle_tpu import static
        stack = _safe_stack()
        stack.eval()
        x_np = RNG.standard_normal((2, 3, 8, 8)).astype(np.float32)
        ref = np.asarray(stack(paddle.to_tensor(x_np))._data)
        cl = to_channels_last(stack, force=True)
        main = static.Program()
        with static.program_guard(main):
            x = static.data('x', [None, 3, 8, 8], 'float32')
            y = cl(x)
        exe = static.Executor()
        out, = exe.run(main, feed={'x': x_np}, fetch_list=[y])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
        paddle.disable_static()

    def test_to_static_inherits_layout(self):
        """jit.to_static over a converted region traces the NHWC ops —
        same numbers, no extra plumbing."""
        stack = _safe_stack()
        stack.eval()
        x = t((2, 3, 8, 8))
        ref = np.asarray(stack(x)._data)
        cl = to_channels_last(stack, force=True)
        st = paddle.jit.to_static(cl)
        np.testing.assert_allclose(np.asarray(st(x)._data), ref,
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_accum_policy_eval_only(self):
        """conv_accum_fp32 requests fp32 accumulation for bf16 convs and
        returns bf16; outside the context the dtype chain is untouched."""
        import jax.numpy as jnp

        from paddle_tpu.nn.functional.conv import conv_accum_fp32
        x = t((1, 4, 8, 8)).astype("bfloat16")
        w = t((8, 4, 3, 3)).astype("bfloat16")
        ref = F.conv2d(x, w, padding=1)
        assert ref._data.dtype == jnp.bfloat16
        with conv_accum_fp32():
            out = F.conv2d(x, w, padding=1)
        assert out._data.dtype == jnp.bfloat16
        # fp32 accumulation must stay within bf16 rounding of the ref
        np.testing.assert_allclose(
            np.asarray(out._data, dtype=np.float32),
            np.asarray(ref._data, dtype=np.float32), rtol=0.05, atol=0.05)

    def test_padding_mode_reflect(self):
        """Conv2D padding_mode pre-pads the input (was silently ignored)."""
        paddle.seed(6)
        conv = nn.Conv2D(3, 5, 3, padding=1, padding_mode="reflect")
        x = t((1, 3, 8, 8))
        out = conv(x)
        assert tuple(out.shape) == (1, 5, 8, 8)
        # equals explicit reflect-pad + unpadded conv
        xp = F.pad(x, [1, 1, 1, 1], mode="reflect")
        ref = F.conv2d(xp, conv.weight, conv.bias)
        np.testing.assert_array_equal(np.asarray(out._data),
                                      np.asarray(ref._data))


# defined LAST: mutates the shared resnet_pair weights (fold); tier-1
# runs with -p no:randomly, preserving file order
class TestFoldResnet:
    def test_resnet18_fold_parity(self, resnet_pair):
        m, cl = resnet_pair
        x = t((2, 3, 32, 32))
        before = np.asarray(cl(x)._data)
        folded = fold_conv_bn(cl)
        assert len(folded) == 20  # every BN in resnet18
        out = np.asarray(cl(x)._data)
        # error accumulates through 20 folded layers; relative to the
        # logit scale it stays at the 1e-5 fp32 contract
        np.testing.assert_allclose(out, before, rtol=2e-5, atol=2e-5)
