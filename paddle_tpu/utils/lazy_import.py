"""Reference spelling: python/paddle/utils/lazy_import.py (try_import of
optional dependencies). Implementation in utils/__init__.py."""
from . import try_import

__all__ = ["try_import"]
