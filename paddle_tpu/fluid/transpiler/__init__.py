"""Reference: python/paddle/fluid/transpiler/ — the 1.x distributed
program transpilers (DistributeTranspiler rewrote a program into
trainer/pserver halves for the parameter-server runtime).

Single-controller adaptation: there are no pserver processes to emit —
every parameter lives mesh-sharded inside the one compiled program (see
distributed/ps for the TPU-native PS analog). transpile() therefore
validates and records the request, get_trainer_program() returns the
program itself (training is collective), and get_pserver_program()
raises with guidance rather than emitting a program that could never
run here.
"""
from __future__ import annotations

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "PSDispatcher", "HashName", "RoundRobin",
           "memory_optimize", "release_memory"]


class DistributeTranspilerConfig:
    """Reference transpiler/distribute_transpiler.py config: field names
    kept; slice_var_up/min_block_size shaped the pserver var split,
    which GSPMD handles via shardings here."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = RoundRobin
        self.min_block_size = 8192
        self.enable_dc_asgd = False
        self.mode = "pserver"
        self.print_log = False
        self.wait_port = True
        self.sync_mode = True
        self.runtime_split_send_recv = False
        self.geo_sgd_mode = False
        self.geo_sgd_need_push_nums = 100


class PSDispatcher:
    """Reference transpiler/ps_dispatcher.py PSDispatcher base: custom
    split_method implementations subclass this and override dispatch."""

    def __init__(self, pserver_endpoints=None):
        self._eps = list(pserver_endpoints or [])

    def dispatch(self, varlist):
        raise NotImplementedError

    def reset(self):
        self._i = 0


class HashName(PSDispatcher):
    """Reference transpiler/ps_dispatcher.py HashName."""

    def dispatch(self, varlist):
        if not self._eps:
            return []
        return [self._eps[hash(v.name if hasattr(v, "name") else str(v))
                          % len(self._eps)] for v in varlist]


class RoundRobin(PSDispatcher):
    """Reference transpiler/ps_dispatcher.py RoundRobin."""

    def __init__(self, pserver_endpoints=None):
        super().__init__(pserver_endpoints)
        self._i = 0

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            if not self._eps:
                break
            out.append(self._eps[self._i % len(self._eps)])
            self._i += 1
        return out


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False
        self._program = None
        self._startup = None

    def transpile(self, trainer_id, program=None, pservers="",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint=""):
        from ..framework import default_main_program

        self.trainer_id = int(trainer_id)
        self.trainer_num = int(trainers)
        self.pserver_endpoints = [p for p in pservers.split(",") if p]
        self._program = (program if program is not None
                         else default_main_program())
        self._startup = startup_program
        self._transpiled = True

    def get_trainer_program(self, wait_port=True):
        if not self._transpiled:
            raise RuntimeError("call transpile() first")
        # collective single-controller: the trainer program IS the
        # program — parameters are mesh-sharded, not pserver-hosted
        return self._program

    def get_pserver_program(self, endpoint):
        raise RuntimeError(
            "No pserver program exists on the TPU build: parameter "
            "serving is replaced by mesh-sharded tables inside the "
            "compiled step (paddle.distributed.ps / rec.ShardedEmbedding)."
            " Run the trainer program on every host instead.")

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        if not self._transpiled:
            raise RuntimeError("call transpile() first")
        if self._startup is not None:
            return self._startup
        # transpile() was called without startup_program: hand back the
        # ambient startup program rather than None (Executor.run(None)
        # would execute the MAIN program)
        from ..framework import default_startup_program

        return default_startup_program()


def memory_optimize(input_program=None, skip_opt_set=None,
                    print_log=False, level=0, skip_grads=True):
    """Reference transpiler/memory_optimization_transpiler.py: a no-op
    since XLA owns buffer reuse/liveness on TPU."""
    return None


def release_memory(input_program=None, skip_opt_set=None):
    return None
