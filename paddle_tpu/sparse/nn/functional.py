"""Sparse functional activations.

Reference: python/paddle/incubate/sparse/nn/functional (relu, relu6,
leaky_relu, softmax). relu/relu6/leaky_relu are zero-preserving so they
apply value-wise; softmax is per-row over the stored entries (absent
entries are treated as -inf, matching the reference kernel).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor import apply
from ..tensor import SparseCooTensor, SparseCsrTensor, is_sparse
from .conv import conv3d, max_pool3d, subm_conv3d  # noqa: F401


def relu(x, name=None):
    if not is_sparse(x):
        raise TypeError("sparse relu expects a sparse tensor")
    return x._map_values(lambda v: jnp.maximum(v, 0))


def relu6(x, name=None):
    if not is_sparse(x):
        raise TypeError("sparse relu6 expects a sparse tensor")
    return x._map_values(lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01, name=None):
    if not is_sparse(x):
        raise TypeError("sparse leaky_relu expects a sparse tensor")
    return x._map_values(
        lambda v: jnp.where(v >= 0, v, v * negative_slope))


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over stored entries (axis must be the last sparse
    dim, as in the reference CSR kernel). Entries are grouped by ALL
    leading sparse dims, so batched COO normalizes per row, not per
    batch."""
    want_csr = isinstance(x, SparseCsrTensor)
    c = x.to_sparse_coo() if want_csr else x.coalesce()
    nsp = c.sparse_dim
    if axis not in (-1, nsp - 1):
        raise ValueError("sparse softmax supports the last sparse axis only")
    if nsp == 1:
        rows = jnp.zeros_like(c._indices[0])
        n_rows = 1
    else:
        import numpy as np
        lead = np.asarray(c._indices[:-1])
        lead_shape = tuple(c.shape[:nsp - 1])
        rows = jnp.asarray(
            np.ravel_multi_index(tuple(lead), lead_shape).astype(np.int32))
        n_rows = int(np.prod(lead_shape))

    def _softmax(v):
        row_max = jax.ops.segment_max(v, rows, num_segments=n_rows)
        row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
        e = jnp.exp(v - row_max[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=n_rows)
        return e / denom[rows]

    vals = apply(_softmax, c._values)
    out = SparseCooTensor(c._indices, vals, c.shape, coalesced=True)
    return out.to_sparse_csr() if want_csr else out


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse attention: softmax(QK^T/sqrt(d) restricted to the stored
    entries of ``sparse_mask``) @ V.

    Reference: incubate/sparse/nn/functional/transformer.py:attention
    (CUDA-11.7 CSR kernel). TPU-first: the mask layout is static host
    data, so scores are computed only at the nnz (row, col) sites via
    dense gathers and normalized with segment reductions — O(nnz) memory
    instead of O(L^2), fully jittable.

    ``query/key/value``: dense [batch, heads, seqlen, head_dim].
    ``sparse_mask``: SparseCsrTensor [L, L] or SparseCooTensor with 2
    sparse dims — the layout shared by every (batch, head) pair (the
    reference requires identical nnz per batch for the same reason).
    ``key_padding_mask`` [batch, L] and ``attn_mask`` [L, L] are additive
    float masks (use -inf to exclude a key).
    """
    import numpy as np

    from ..tensor import SparseCooTensor as _Coo, SparseCsrTensor as _Csr
    if isinstance(sparse_mask, _Csr):
        coo = sparse_mask.to_sparse_coo()
    elif isinstance(sparse_mask, _Coo):
        coo = sparse_mask.coalesce()
    else:
        raise TypeError("sparse_mask must be a sparse tensor")
    if coo.sparse_dim < 2:
        raise ValueError("sparse_mask needs 2 sparse dims (rows, cols)")
    rows = jnp.asarray(np.asarray(coo._indices[-2]), jnp.int32)
    cols = jnp.asarray(np.asarray(coo._indices[-1]), jnp.int32)

    b, h, L, d = (int(s) for s in query.shape)
    scale = 1.0 / float(np.sqrt(d))

    def _attend(q, k, v, *masks):
        qf = q.reshape(b * h, L, d)
        kf = k.reshape(b * h, L, d)
        vf = v.reshape(b * h, L, d)
        s = jnp.einsum("ged,ged->ge", qf[:, rows, :], kf[:, cols, :])
        s = (s * scale).T  # (nnz, BH): segment ops reduce the lead axis
        mi = 0
        if key_padding_mask is not None:
            kp = masks[mi]; mi += 1
            kp = jnp.repeat(kp.astype(s.dtype), h, axis=0)  # (BH, L)
            s = s + kp[:, cols].T
        if attn_mask is not None:
            am = masks[mi].astype(s.dtype)
            s = s + am[rows, cols][:, None]
        smax = jax.ops.segment_max(s, rows, num_segments=L)
        smax = jnp.where(jnp.isfinite(smax), smax, 0.0)
        e = jnp.exp(s - smax[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=L)
        p = e / jnp.maximum(denom[rows], 1e-38)
        ctx = jnp.zeros((L, b * h, d), p.dtype).at[rows].add(
            p[:, :, None] * jnp.swapaxes(vf, 0, 1)[cols])
        return jnp.swapaxes(ctx, 0, 1).reshape(b, h, L, d)

    extra = tuple(m for m in (key_padding_mask, attn_mask) if m is not None)
    return apply(_attend, query, key, value, *extra)
