"""tpu_lint front ends: build a :class:`ProgramView` from whatever the
caller has — a jittable callable + example args, a Layer, raw StableHLO
text, a static-executor replay plan, a serving Engine, or the live
eager-dispatch cache — then run every registered program rule over it.
``selflint`` is the AST front end over python source files.
"""
from __future__ import annotations

import os

from . import rules_ast as _rules_ast  # noqa: F401  (registers rules)
from . import rules_program as _rules_prog  # noqa: F401  (registers rules)
from .findings import Report
from .hlo import parse_stablehlo
from .registry import iter_rules
from .rules_ast import SourceFile

# most recent reports, surfaced as one line in profiler.Profiler.summary()
_last_report = None


class ProgramView:
    """One audited program: lowered StableHLO text (parsed lazily),
    optionally the traced jaxpr, plus origin metadata the meta-level
    rules (plan/engine/dispatch) read."""

    def __init__(self, name, kind, stablehlo=None, jaxpr=None, meta=None):
        self.name = name
        self.kind = kind            # callable|stablehlo|plan|engine|dispatch
        self.stablehlo = stablehlo
        self.jaxpr = jaxpr          # ClosedJaxpr or None
        self.meta = dict(meta or {})
        self.metrics = {}
        self._module = None

    @property
    def module(self):
        if self._module is None and self.stablehlo:
            self._module = parse_stablehlo(self.stablehlo)
        return self._module

    def iter_eqns(self):
        """(eqn, path) over the jaxpr, recursing into sub-jaxprs
        (pjit/scan/cond bodies)."""
        if self.jaxpr is None:
            return
        yield from _walk_jaxpr(getattr(self.jaxpr, "jaxpr", self.jaxpr),
                               "")

    def run_rules(self, rules=None) -> Report:
        global _last_report
        report = Report(origin=f"{self.kind}:{self.name}")
        for r in iter_rules(kind="program", ids=rules):
            for f in r.run(self):
                report.add(f)
        report.metrics.update(self.metrics)
        _last_report = report
        return report


def _walk_jaxpr(jaxpr, prefix):
    for i, eqn in enumerate(jaxpr.eqns):
        path = f"{prefix}eqn[{i}]:{eqn.primitive.name}"
        yield eqn, path
        for sub in _sub_jaxprs(eqn.params):
            yield from _walk_jaxpr(sub, path + "/")


def _sub_jaxprs(params):
    for v in params.values():
        yield from _as_jaxprs(v)


def _as_jaxprs(v):
    # ClosedJaxpr / Jaxpr duck-typing: avoids importing private core
    if hasattr(v, "eqns"):
        yield v
    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _as_jaxprs(x)


# -- callable / model front end ---------------------------------------------

def _is_tensorish(fn, flat_args):
    from ..nn.layer_base import Layer
    from ..tensor import Tensor
    if isinstance(fn, Layer) or isinstance(getattr(fn, "__self__", None),
                                           Layer):
        return True
    return any(isinstance(a, Tensor) for a in flat_args)


def _unhashable_statics(args, kwargs):
    import jax
    import numpy as np
    out = []
    flat, _ = jax.tree_util.tree_flatten_with_path((args, kwargs))
    for path, leaf in flat:
        if isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
            continue
        try:
            hash(leaf)
        except TypeError:
            out.append((jax.tree_util.keystr(path),
                        type(leaf).__name__))
    return out


def _aliased_donations(args, donate_argnums):
    import jax
    if not donate_argnums:
        return []
    ids = {}
    out = []
    for i, a in enumerate(args):
        for leaf in jax.tree_util.tree_leaves(a):
            if not hasattr(leaf, "dtype"):
                continue
            j = ids.setdefault(id(leaf), i)
            if j != i and (i in donate_argnums or j in donate_argnums):
                out.append(f"args {j} and {i} share a buffer")
    return out


def audit(fn, *args, donate_argnums=(), name=None, rules=None,
          **kwargs) -> Report:
    """Trace + lower ``fn`` on the example arguments and run every
    program rule over the jaxpr and emitted StableHLO.

    Accepts plain jax-array callables (lowered directly, honoring
    ``donate_argnums``) and paddle Tensor/Layer callables (lowered
    through ``jit.to_static``'s StaticFunction, which hoists Layer
    parameters into jit arguments).
    """
    import jax

    flat_args = jax.tree_util.tree_leaves((args, kwargs))
    label = name or getattr(fn, "__name__", None) or type(fn).__name__
    meta = {"unhashable_statics": _unhashable_statics(args, kwargs),
            "aliased_donations": _aliased_donations(args, donate_argnums),
            "donate_argnums": tuple(donate_argnums)}

    text = None
    jaxpr = None
    try:
        if _is_tensorish(fn, flat_args):
            from ..nn.layer_base import Layer
            target = fn.forward if isinstance(fn, Layer) else fn
            from ..jit.api import StaticFunction
            sf = StaticFunction(target, convert_control_flow=False)
            text = sf.lower(*args, **kwargs).as_text()
        else:
            jfn = jax.jit(fn, donate_argnums=tuple(donate_argnums))
            text = jfn.lower(*args, **kwargs).as_text()
            try:
                jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
            except Exception as e:
                meta["jaxpr_error"] = f"{type(e).__name__}: {e}"
    except Exception as e:
        # un-lowerable example args (unhashable statics, non-array
        # leaves) are themselves a finding, not an audit crash: record
        # why and let retrace-risk report the offending leaves
        meta["lowering_error"] = f"{type(e).__name__}: {str(e)[:200]}"

    view = ProgramView(label, "callable", stablehlo=text, jaxpr=jaxpr,
                       meta=meta)
    return view.run_rules(rules)


def audit_model(model, *args, rules=None, **kwargs) -> Report:
    """Audit a Layer's jitted forward on example inputs (params hoisted
    as jit arguments, exactly what ``jit.to_static`` would compile)."""
    return audit(model, *args, rules=rules,
                 name=type(model).__name__, **kwargs)


def audit_stablehlo(text, name="stablehlo", rules=None) -> Report:
    """Audit an already-lowered StableHLO module (text form)."""
    return ProgramView(name, "stablehlo", stablehlo=text).run_rules(rules)


# -- plan / engine / dispatch front ends ------------------------------------

def _describe_entry(e):
    try:
        kind = e[0]
        if kind == "op":
            fn = e[1]
            label = getattr(fn, "__name__", type(fn).__name__)
            return f"op:{label}"
        return str(kind)
    except (AttributeError, IndexError, TypeError):
        return "host entry"


def audit_plan(plan_or_program, *batch, rules=None,
               name="replay_plan") -> Report:
    """Audit a static-executor replay plan (or every cached plan of a
    ``static.Program``): host splits, donation, fragmentation. A Fleet
    train step (anything exposing ``lower_hlo``) delegates to
    :func:`audit_train_step`, so the one entry point covers both
    compiled-training front ends."""
    from ..static.program import _ReplayPlan

    if hasattr(plan_or_program, "lower_hlo"):
        return audit_train_step(plan_or_program, *batch, rules=rules)
    if not isinstance(plan_or_program, _ReplayPlan):
        cache = getattr(plan_or_program, "_jit_cache", None) or {}
        plans = [p for p in cache.values() if p is not None]
        if not plans:
            raise ValueError(
                "program has no compiled replay plan yet — run the "
                "Executor at least twice so the plan builds")
        report = Report(origin=f"plan:{name}")
        for i, p in enumerate(plans):
            report.extend(audit_plan(p, rules=rules, name=f"{name}[{i}]"))
        global _last_report
        _last_report = report
        return report

    plan = plan_or_program
    host_entries = []
    segments = []
    for idx, (kind, payload) in enumerate(plan.steps):
        if kind == "host":
            host_entries.append((_describe_entry(payload), idx))
        else:
            segments.append({
                "index": idx, "donated": payload.donated,
                "n_state": len(payload.state_specs),
                "alias_count": payload.alias_count})
    meta = {"host_entries": host_entries, "segments": segments,
            "n_segments": len(segments), "n_host": plan.n_host,
            # segmented plans can't donate by design: don't double-count
            # the donation finding on top of the host-split finding
            "segmented": len(segments) > 1}
    return ProgramView(name, "plan", meta=meta).run_rules(rules)


def audit_train_step(step, *batch, rules=None) -> Report:
    """Audit a compiled Fleet train step (``CompiledTrainStep`` or
    ``distributed.comm_opt.CommOptTrainStep``) on an example batch: the
    REAL step program — forward, backward, gradient exchange and the
    optimizer update — is lowered and every program rule runs over its
    StableHLO. The ``unoverlapped-collective`` rule is the headline:
    a TP training matmul whose collective serializes after the dot
    (the GSPMD/serial form) is a high finding here, exactly like
    ``audit_engine`` gates the serving decode program."""
    meta = {"train_step": type(step).__name__}
    for attr in ("grad_compress", "zero1", "tp_overlap", "dp", "tp",
                 "stage", "accumulate_steps"):
        if hasattr(step, attr):
            meta[attr] = getattr(step, attr)
    try:
        from ..aot import aot_stats
        meta["aot"] = aot_stats()
    except Exception as e:
        meta["aot_error"] = f"{type(e).__name__}: {e}"
    text = None
    try:
        text = step.lower_hlo(*batch)
    except Exception as e:
        meta["lowering_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    return ProgramView(type(step).__name__, "train_step", stablehlo=text,
                       meta=meta).run_rules(rules)


def audit_engine(engine, compile_budget=None, rules=None,
                 lower_decode=True) -> Report:
    """Audit a serving Engine: compile-count budget, bucket/KV geometry,
    donation policy — plus, when possible, the lowered decode program
    itself (dtype / padding rules see real HLO).

    Accepts a ``serving.resilience.EngineSupervisor`` too: the live
    engine incarnation is audited, and the compile budget accounts the
    UNION of prefill buckets across every rebuilt incarnation — an
    in-process rebuild re-traces nothing (module-level jit cache), but a
    fresh process pays the union, so that is the honest budget."""
    import jax

    from .engine_support import engine_donates, lower_decode_program

    supervisor = None
    if hasattr(engine, "buckets_seen_total") and hasattr(engine, "engine"):
        supervisor = engine
        engine = supervisor.engine
    buckets = set(engine.buckets_seen)
    chunk_used = bool(getattr(engine, "chunk_used", False))
    verify_used = bool(getattr(engine, "verify_used", False))
    draft_buckets = set(getattr(engine, "draft_buckets_seen", ()))
    draft_decode = bool(getattr(engine, "draft_decode_used", False))
    if supervisor is not None:
        buckets |= supervisor.buckets_seen_total
        chunk_used |= bool(getattr(supervisor, "chunk_used_total", False))
        verify_used |= bool(getattr(supervisor, "verify_used_total",
                                    False))
        draft_buckets |= set(getattr(supervisor, "draft_buckets_total",
                                     ()))
        draft_decode |= bool(getattr(supervisor,
                                     "draft_decode_used_total", False))
    meta = {
        "n_slots": engine.n_slots, "max_len": engine.max_len,
        "min_prompt_bucket": engine.min_prompt_bucket,
        "buckets_seen": sorted(buckets),
        "decode_used": engine.metrics.decode_steps > 0
        or bool(buckets),
        "compile_budget": (compile_budget if compile_budget is not None
                           else engine.compile_budget),
        "backend": jax.default_backend(),
        "donate": engine_donates(engine),
        "kv_heads": engine.cache.kv_heads,
        "head_dim": engine.cache.head_dim,
        "kv_layout": getattr(engine, "kv_layout", "slot"),
        "block_size": getattr(engine, "block_size", None),
        "n_blocks": (engine.cache.pool.n_blocks
                     if hasattr(engine.cache, "pool") else None),
        "prefill_chunk": getattr(engine, "prefill_chunk", None),
        "chunk_used": chunk_used,
        "tp": getattr(engine, "tp", 1),
        "mesh": (engine.tp_geometry()
                 if hasattr(engine, "tp_geometry") else None),
    }
    spec = getattr(engine, "spec", None)
    if spec is not None:
        # speculative config + program usage (the compile-budget rule
        # counts the verify program and any draft-model lowerings) and
        # the acceptance ledger — across supervisor incarnations when
        # audited through one
        m = engine.metrics
        acc = {k: getattr(m, k, 0)
               for k in ("spec_steps", "draft_steps",
                         "spec_proposed_tokens", "spec_accepted_tokens",
                         "spec_emitted_tokens")}
        if supervisor is not None and hasattr(supervisor,
                                              "spec_counters"):
            acc = supervisor.spec_counters()
        rate = (acc["spec_accepted_tokens"] / acc["spec_proposed_tokens"]
                if acc["spec_proposed_tokens"] else None)
        meta["spec"] = {
            "k": spec.k, "draft": spec.draft_kind(),
            "verify_used": verify_used,
            "draft_buckets_seen": sorted(draft_buckets),
            "draft_decode_used": draft_decode,
            "acceptance": {**acc, "rate": rate}}
    # AOT warm-start visibility: programs restored from the executable
    # cache cost a fresh process zero backend compiles — the honest
    # warm-start compile count is programs minus disk-exec entries
    try:
        from ..aot import aot_stats
        sources = engine.aot_stats() if hasattr(engine, "aot_stats") \
            else {}
        # "live" programs have no persisted entry (a restart compiles
        # them); "compiled" ones were persisted at build and "disk-exec"
        # ones restored — both cost a warm restart nothing; "disk-hlo"
        # pays one recompile-from-StableHLO
        meta["aot"] = {**aot_stats(), "engine_programs": sources,
                       "warm_start_compiles": sum(
                           n for k, n in sources.items()
                           if k in ("live", "disk-hlo"))}
    except Exception as e:
        meta["aot_error"] = f"{type(e).__name__}: {e}"
    if supervisor is not None:
        meta["supervisor"] = {"rebuilds": supervisor.rebuilds,
                              "replayed": supervisor.replayed}
    text = None
    if lower_decode:
        try:
            text = lower_decode_program(engine)
        except Exception as e:
            meta["decode_lowering_error"] = f"{type(e).__name__}: {e}"
    return ProgramView(f"Engine[{type(engine).__name__}]", "engine",
                       stablehlo=text, meta=meta).run_rules(rules)


def audit_fleet(fleet, compile_budget=None, rules=None,
                lower_decode=False) -> Report:
    """Audit a ``serving.fleet.ReplicaFleet``: the compile budget is the
    UNION of prefill buckets (+ decode + chunk) across EVERY replica and
    every supervisor-rebuilt incarnation — in-process the replicas share
    the module-level jitted programs, so an N-replica fleet legitimately
    budgets as ONE engine (0 extra lowerings is the fleet contract,
    gated by ``tools/check_serving_compiles.py --fleet N``), and a fresh
    process pays exactly that union. Geometry/donation meta comes from
    replica 0 (fleet replicas share engine kwargs; tp degree may vary
    per replica and is reported per replica)."""
    import jax

    from .engine_support import engine_donates

    replicas = list(fleet.replicas.values())
    buckets: set = set()
    chunk_used = False
    decode_used = False
    per_replica = {}
    for rep in replicas:
        sup = rep.sup
        b = set(sup.engine.buckets_seen) | sup.buckets_seen_total
        buckets |= b
        chunk_used |= (bool(getattr(sup.engine, "chunk_used", False))
                       or bool(sup.chunk_used_total))
        decode_used |= sup.engine.metrics.decode_steps > 0 or bool(b)
        per_replica[rep.id] = {
            "state": rep.state, "tp": sup.engine.tp,
            "buckets_seen": sorted(b), "rebuilds": sup.rebuilds,
            "replayed": sup.replayed}
    first = replicas[0].engine
    if compile_budget is None:
        compile_budget = first.compile_budget
    meta = {
        "n_slots": first.n_slots, "max_len": first.max_len,
        "min_prompt_bucket": first.min_prompt_bucket,
        "buckets_seen": sorted(buckets),
        "decode_used": decode_used,
        "compile_budget": compile_budget,
        "backend": jax.default_backend(),
        "donate": engine_donates(first),
        "kv_heads": first.cache.kv_heads,
        "head_dim": first.cache.head_dim,
        "kv_layout": first.kv_layout,
        "block_size": first.block_size,
        "n_blocks": (first.cache.pool.n_blocks
                     if hasattr(first.cache, "pool") else None),
        "prefill_chunk": first.prefill_chunk,
        "chunk_used": chunk_used,
        "fleet": {"name": fleet.name, "n_replicas": len(replicas),
                  "states": fleet.replica_states(),
                  "counters": fleet.counters(),
                  "per_replica": per_replica},
    }
    text = None
    if lower_decode:
        from .engine_support import lower_decode_program
        try:
            text = lower_decode_program(first)
        except Exception as e:
            meta["decode_lowering_error"] = f"{type(e).__name__}: {e}"
    report = ProgramView(f"ReplicaFleet[{len(replicas)}]", "engine",
                         stablehlo=text, meta=meta).run_rules(rules)
    # the fleet view rides in the report's measurements (Report carries
    # metrics, not meta) — tools embed it in their JSON ledgers
    report.metrics["fleet"] = meta["fleet"]
    return report


def audit_dispatch(rules=None) -> Report:
    """Audit the live eager-dispatch cache: blacklisted ops (with the
    recorded reason), megamorphic signatures, retrace pressure — plus
    the AOT compile-service view (warm-start compile counts with the
    executable cache enabled, key-instability findings)."""
    from ..aot import aot_stats
    from ..framework.dispatch_cache import dispatch_stats

    meta = {"dispatch_stats": dispatch_stats(), "aot": aot_stats()}
    return ProgramView("eager-dispatch", "dispatch",
                       meta=meta).run_rules(rules)


# -- AST self-lint front end -------------------------------------------------

def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def selflint(paths, rules=None) -> Report:
    """Run the AST rules over python source files/directories."""
    global _last_report
    report = Report(origin=f"selflint:{','.join(map(str, paths))}")
    n_files = 0
    for path in _iter_py_files(paths):
        n_files += 1
        sf = SourceFile.load(path)
        if sf.parse_error:
            from .findings import Finding
            report.add(Finding("parse-error", "info", sf.parse_error,
                               location=path))
            continue
        for r in iter_rules(kind="ast", ids=rules):
            for f in r.run(sf):
                report.add(f)
    report.metrics["selflint"] = {"files": n_files}
    _last_report = report
    return report


def findings_summary():
    """One-line summary of the most recent audit (None when nothing has
    been audited yet) — wired into profiler.Profiler.summary()."""
    if _last_report is None:
        return None
    return _last_report.summary_line()
