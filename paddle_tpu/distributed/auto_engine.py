"""auto_parallel Engine: automatic sharding plan + compiled train step.

Reference: python/paddle/distributed/auto_parallel/engine.py:55 — Engine
takes (model, loss, optimizer, strategy), plans a distributed program with
its cost model, and exposes fit/evaluate/predict. TPU-native version: the
"plan" is a PartitionSpec per parameter over the global mesh; candidate
plans are generated from structure (Megatron-style TP for large matmuls,
ZeRO-3 over the sharding axis, replication otherwise), scored by a memory
model (fits-in-HBM first, then per-device bytes), optionally cross-checked
with XLA's cost_analysis, and the winner feeds the same CompiledTrainStep
the manual Fleet path uses — GSPMD then materializes the collectives.

Op-level planning (reference partitioner.py/reshard.py analog):
``plan_activations`` searches explicit with_sharding_constraint layouts
for the major activation sites on top of the parameter plan, keeping a
constraint only when the compiled cost (reshards included) beats
GSPMD's inference — see the "op-level (activation) planning" section.
Pipeline (pp) placement is the pipeline train step's own schedule
(ops/pipeline.py, fleet/pp_train_step.py), not planned here.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod

# optimizer moments (2 fp32 per param) + master weights, relative to a
# bf16/fp32 param copy — used by the planner's memory model
_OPT_STATE_MULT = 3.0


def _divisible_dims(shape, size):
    return [d for d in range(len(shape)) if shape[d] % size == 0]


class Plan:
    """One candidate sharding assignment."""

    def __init__(self, name, specs, bytes_per_device, seed=False):
        self.name = name
        self.specs = specs  # param name -> PartitionSpec
        self.bytes_per_device = bytes_per_device
        self.seed = seed  # structurally distinct seed vs per-param refine

    def __repr__(self):
        return (f"Plan({self.name}, "
                f"{self.bytes_per_device / 2**30:.2f} GiB/device)")


class Engine:
    """Plan shardings automatically, then train/evaluate with them.

    Usage:
        engine = auto_parallel.Engine(model, loss_fn, optimizer)
        plan = engine.plan()              # chosen sharding plan
        step = engine.prepare()           # CompiledTrainStep with the plan
        loss = step(batch_x, batch_y)
    """

    def __init__(self, model, loss_fn: Optional[Callable] = None,
                 optimizer=None, strategy=None,
                 hbm_budget_bytes: Optional[int] = None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.strategy = strategy
        self.mesh = mesh_mod.get_mesh()
        # per-device HBM working budget (default 12 GiB of a 16 GiB chip,
        # leaving headroom for activations/XLA scratch)
        self.hbm_budget = hbm_budget_bytes or 12 * 2**30
        # how many of the largest params get per-param candidate
        # refinement plans (search breadth / compile-time knob)
        self.refine_top_k = 8
        self._plan = None
        self.last_costs = {}  # plan name -> compiled cost, after plan()
        # op-level planning state (reference auto_parallel annotates
        # every operator/activation with a dist_attr; here the major
        # activation sites get explicit with_sharding_constraint specs
        # when they beat GSPMD's inference on compiled cost)
        self.activation_specs = {}  # sublayer path -> PartitionSpec
        self.last_activation_costs = {}
        self._act_handles = []

    # -- candidate generation ------------------------------------------------

    def _params(self):
        return dict(self.model.named_parameters())

    def _bytes(self, specs):
        """Per-device parameter+optimizer bytes under `specs`."""
        total = 0.0
        for name, p in self._params().items():
            n = float(np.prod(p._data.shape)) or 1.0
            itemsize = np.dtype(p._data.dtype).itemsize
            shard = 1.0
            spec = specs.get(name) or P()
            for axes in spec:
                if axes is None:
                    continue
                for ax in (axes if isinstance(axes, tuple) else (axes,)):
                    shard *= self.mesh.shape.get(ax, 1)
            total += n * itemsize * (1 + _OPT_STATE_MULT) / shard
        return total

    def param_candidates(self, name, shape, declared=None):
        """ALL valid placements for one parameter, generated from mesh
        divisibility (reference auto_parallel/planner.py enumerates
        per-op dist_attrs the same way): every assignment of the >1-sized
        model axes ("tp", "sharding", and their composite) onto divisible
        dims, plus replicated. A declared pspec (mp_layers etc.) is kept
        as the first candidate — it encodes operator knowledge the
        planner should prefer at equal cost."""
        shape = tuple(shape)
        cands = []
        if declared is not None:
            cands.append(P(*declared))
        cands.append(P())
        axes = [a for a in ("tp", "sharding")
                if self.mesh.shape.get(a, 1) > 1]
        options = [(a,) for a in axes]
        if len(axes) == 2:
            options.append(tuple(axes))  # composite ("tp","sharding")
        for opt in options:
            size = 1
            for a in opt:
                size *= self.mesh.shape[a]
            for d in _divisible_dims(shape, size):
                spec = [None] * len(shape)
                spec[d] = opt if len(opt) > 1 else opt[0]
                cands.append(P(*spec))
            if len(opt) == 2 and len(shape) >= 2:
                # one axis per dim (e.g. P("tp","sharding")) — valid when
                # each dim divides its own axis
                for d0 in _divisible_dims(shape, self.mesh.shape[opt[0]]):
                    for d1 in _divisible_dims(shape,
                                              self.mesh.shape[opt[1]]):
                        if d0 == d1:
                            continue
                        spec = [None] * len(shape)
                        spec[d0], spec[d1] = opt[0], opt[1]
                        cands.append(P(*spec))
        seen, out = set(), []
        for c in cands:
            key = tuple(c)
            if key not in seen:
                seen.add(key)
                out.append(c)
        return out

    def _candidates(self):
        tp = self.mesh.shape.get("tp", 1)
        shd = self.mesh.shape.get("sharding", 1)
        params = self._params()

        def replicated():
            return {k: (p.pspec or P()) for k, p in params.items()}

        plans = []
        base = replicated()
        plans.append(Plan("replicated(dp-only)", base, self._bytes(base),
                          seed=True))

        if tp > 1:
            specs = {}
            flip = True
            for k, p in params.items():
                shape = tuple(p._data.shape)
                spec = list(p.pspec) if p.pspec else []
                spec += [None] * (len(shape) - len(spec))
                if (p.pspec is None and len(shape) == 2
                        and np.prod(shape) >= 2**16):
                    # Megatron pairing: alternate column/row splits so an
                    # in-proj/out-proj pair needs one collective, not two
                    dims = _divisible_dims(shape, tp)
                    if dims:
                        d = dims[-1] if flip else dims[0]
                        spec[d] = "tp"
                        flip = not flip
                specs[k] = P(*spec)
            plans.append(Plan("tp(megatron-alt)", specs, self._bytes(specs),
                              seed=True))

        if shd > 1:
            for src in list(plans):
                specs = {}
                for k, p in params.items():
                    shape = tuple(p._data.shape)
                    spec = list(src.specs[k])
                    spec += [None] * (len(shape) - len(spec))
                    if np.prod(shape) >= 1024:
                        cands = [d for d in range(len(shape))
                                 if spec[d] is None and shape[d] % shd == 0]
                        if cands:
                            d = max(cands, key=lambda i: shape[i])
                            spec[d] = "sharding"
                    specs[k] = P(*spec)
                plans.append(Plan(f"{src.name}+zero3", specs,
                                  self._bytes(specs), seed=True))

        # per-param refinements off the most structured seed: for the
        # largest params, swap in each generated candidate placement —
        # the search space the fixed seeds can't reach
        seed = plans[-1]
        sizes = sorted(((float(np.prod(p._data.shape)), k)
                        for k, p in params.items()), reverse=True)
        for _, k in sizes[:self.refine_top_k]:
            p = params[k]
            for cand in self.param_candidates(
                    k, p._data.shape, declared=p.pspec)[:6]:
                if tuple(cand) == tuple(seed.specs[k]):
                    continue
                specs = dict(seed.specs)
                specs[k] = cand
                plans.append(Plan(f"refine[{k}->{tuple(cand)}]", specs,
                                  self._bytes(specs)))
        return plans

    # -- plan selection ------------------------------------------------------

    def plan(self, use_cost_model: bool = False, sample_batch=None,
             max_compiles: int = 8) -> Plan:
        """Pick the cheapest plan that fits the HBM budget (reference:
        auto_parallel planner + cost model). With use_cost_model=True and
        a sample batch, up to ``max_compiles`` surviving candidates are
        compiled WITH their shardings applied and ranked on XLA
        cost_analysis (bytes accessed covers HBM traffic + the inserted
        collectives' buffer movement)."""
        self.last_costs = {}
        plans = self._candidates()
        fitting = [pl for pl in plans if pl.bytes_per_device
                   <= self.hbm_budget]
        pool = fitting or sorted(plans,
                                 key=lambda pl: pl.bytes_per_device)[:1]
        # least communication first: fewer sharded axes = fewer collectives,
        # so among fitting plans prefer the EARLIEST generated (replicated <
        # tp < +zero3); memory pressure already filtered.
        chosen = pool[0]
        if use_cost_model and sample_batch is not None and len(pool) > 1:
            # rank a bounded prefix: every surviving structural seed first,
            # then the best-by-memory refinements fill the compile budget
            seeds = [pl for pl in pool if pl.seed]
            rest = sorted((pl for pl in pool if not pl.seed),
                          key=lambda pl: pl.bytes_per_device)
            ranked = (seeds + rest)[:max_compiles]
            costs = {id(pl): self._cost(pl, sample_batch) for pl in ranked}
            chosen = min(ranked, key=lambda pl: costs[id(pl)])
            self.last_costs = {pl.name: costs[id(pl)] for pl in ranked}
        self._plan = chosen
        return chosen

    def _cost(self, plan, sample_batch, activation_specs=None):
        """Compiled cost of one fwd+bwd step WITH the plan's shardings
        applied as the parameters' in_shardings (GSPMD propagates from
        there, inserting the collectives the plan implies). With
        ``activation_specs``, the listed sublayers' outputs are pinned
        via with_sharding_constraint during the trace, so the cost
        includes any reshards the constraints force."""
        # any pinned hooks from a previous prepare() must not pollute
        # this measurement — detach, measure, reinstall
        for h in self._act_handles:
            h.remove()
        self._act_handles = []
        handles = self._install_constraints(activation_specs or {})
        try:
            from jax.sharding import NamedSharding

            from ..autograd.tape import functional_mode
            from ..jit.api import _swap_params
            from ..tensor import Tensor

            params = self._params()

            def fwd(pv, batch):
                with functional_mode(), _swap_params(params, pv):
                    out = self.loss_fn(self.model, *batch)
                raw = out._data if isinstance(out, Tensor) else out
                return raw.astype(np.float32).sum()

            def step(pv, batch):
                loss, grads = jax.value_and_grad(fwd)(pv, batch)
                return loss, grads

            pv = {k: p._data for k, p in params.items()}
            raw = tuple(b._data if isinstance(b, Tensor) else b
                        for b in sample_batch)
            in_sh = ({k: NamedSharding(self.mesh,
                                       plan.specs.get(k) or P())
                      for k in pv}, None)
            lowered = jax.jit(step, in_shardings=in_sh).lower(pv, raw)
            cost = lowered.compile().cost_analysis()
            if isinstance(cost, list):
                cost = cost[0]
            return float(cost.get("bytes accessed", math.inf))
        except Exception:
            return float(plan.bytes_per_device) * 1e6  # worst-ranked
        finally:
            for h in handles:
                h.remove()
            # reinstall whatever plan_activations() chose — keyed on the
            # chosen specs, not on the (now cleared) old handle list, so
            # a _cost() between plan_activations() and prepare() still
            # measures under the chosen constraints
            if self.activation_specs:
                self._act_handles = self._install_constraints(
                    self.activation_specs)

    # -- op-level (activation) planning --------------------------------------
    #
    # Reference: auto_parallel/{planner.py,partitioner.py,reshard.py} give
    # every operator a dist_attr and insert explicit reshard programs when
    # producer/consumer shardings disagree. The XLA analog: GSPMD already
    # infers activation shardings from the parameter placements, and
    # with_sharding_constraint is the reshard primitive — so the planner's
    # job is to find the activation sites where an EXPLICIT constraint
    # beats GSPMD's inference, measured on compiled cost, and pin exactly
    # those. The constraint mid-graph also lets the plan CHANGE along the
    # program (e.g. TP inside attention, batch-sharded at the small head).

    def _activation_sites(self, max_sites=4):
        """Major activation sites: the largest-parameter leaf sublayers
        (attention/MLP projections, embeddings, the logits head), ordered
        by parameter size — the places whose output layout decides the
        collective pattern."""
        sized = []
        for name, sub in self.model.named_sublayers():
            if any(True for _ in sub.named_sublayers()):
                continue  # leaves only: constraints nest otherwise
            n = sum(float(np.prod(p._data.shape))
                    for _, p in sub.named_parameters())
            if n >= 1024:
                sized.append((n, name, sub))
        sized.sort(key=lambda t: (-t[0], t[1]))
        return [(name, sub) for _, name, sub in sized[:max_sites]]

    def _activation_candidates(self):
        """Candidate output layouts, ndim-agnostic (the hook pads/guards
        at trace time): batch over dp, hidden over tp, batch over
        dp×sharding, and both ends pinned."""
        have = {a for a in ("dp", "tp", "sharding")
                if self.mesh.shape.get(a, 1) > 1}
        cands = []
        if "dp" in have:
            cands.append(("batch-dp", ("dp",)))
        if "tp" in have:
            cands.append(("hidden-tp", ("...", "tp")))
        if {"dp", "tp"} <= have:
            cands.append(("dp+tp", ("dp", "...", "tp")))
        if {"dp", "sharding"} <= have:
            cands.append(("batch-dpxshard", (("dp", "sharding"),)))
        return cands

    def _constraint_hook(self, template):
        """Forward-post hook applying with_sharding_constraint with the
        template expanded to the output's rank; silently passes through
        outputs whose shape can't take the spec (tuple outputs, rank
        too small, non-divisible dims)."""
        from jax.sharding import NamedSharding

        from ..tensor import Tensor

        def expand(nd):
            t = tuple(template)
            if "..." in t:
                i = t.index("...")
                head, tail = t[:i], t[i + 1:]
                if len(head) + len(tail) > nd:
                    return None
                return head + (None,) * (nd - len(head) - len(tail)) + tail
            if len(t) > nd:
                return None
            return t + (None,) * (nd - len(t))

        def axis_size(ax):
            if isinstance(ax, tuple):
                s = 1
                for a in ax:
                    s *= self.mesh.shape.get(a, 1)
                return s
            return self.mesh.shape.get(ax, 1)

        def hook(layer, inputs, output):
            if not isinstance(output, Tensor):
                return None
            raw = output._data
            if not isinstance(raw, jax.core.Tracer):
                # only under jit: rewrapping eagerly would detach the
                # autograd tape, and a constraint means nothing eager
                return None
            sp = expand(raw.ndim)
            if sp is None:
                return None
            for d, ax in enumerate(sp):
                if ax is not None and raw.shape[d] % axis_size(ax):
                    return None
            out = jax.lax.with_sharding_constraint(
                raw, NamedSharding(self.mesh, P(*sp)))
            t = Tensor(out, stop_gradient=output.stop_gradient)
            return t
        return hook

    def _install_constraints(self, specs):
        subs = dict(self.model.named_sublayers())
        handles = []
        for name, template in specs.items():
            sub = subs.get(name)
            if sub is not None:
                handles.append(sub.register_forward_post_hook(
                    self._constraint_hook(template)))
        return handles

    def plan_activations(self, sample_batch, max_compiles=8,
                         max_sites=4):
        """Greedy per-site search over activation layouts on top of the
        chosen parameter plan: a candidate constraint is kept only when
        the COMPILED cost (XLA cost_analysis with the constraint's
        reshard materialized) beats the current best. Returns the kept
        {site: spec-template} map; ``prepare()`` pins them."""
        if self._plan is None:
            self.plan(use_cost_model=True, sample_batch=sample_batch)
        # plan(use_cost_model=True) already compiled the chosen plan —
        # don't pay that compile twice
        baseline = self.last_costs.get(self._plan.name)
        if baseline is None:
            baseline = self._cost(self._plan, sample_batch)
        self.activation_specs = {}
        self.last_activation_costs = {"<param-plan-only>": baseline}
        best = baseline
        compiles = 0
        cands = self._activation_candidates()
        for name, _sub in self._activation_sites(max_sites):
            site_best, site_spec = best, None
            for label, template in cands:
                if compiles >= max_compiles:
                    break
                trial = dict(self.activation_specs)
                trial[name] = template
                cost = self._cost(self._plan, sample_batch,
                                  activation_specs=trial)
                compiles += 1
                self.last_activation_costs[f"{name}:{label}"] = cost
                if cost < site_best:
                    site_best, site_spec = cost, template
            if site_spec is not None:
                self.activation_specs[name] = site_spec
                best = site_best
        self.last_activation_costs["<with-activation-plan>"] = best
        return self.activation_specs

    # -- application ---------------------------------------------------------

    def prepare(self, accumulate_steps=None, scaler=None):
        """Apply the chosen plan to the model's params, pin any winning
        activation constraints, and build the compiled train step."""
        if self._plan is None:
            self.plan()
        for k, p in self._params().items():
            p.pspec = self._plan.specs.get(k, p.pspec)
        for h in self._act_handles:
            h.remove()
        self._act_handles = self._install_constraints(self.activation_specs)
        from .fleet.train_step import make_train_step
        if self.optimizer is None or self.loss_fn is None:
            raise ValueError("Engine.prepare needs optimizer and loss_fn")
        self._step = make_train_step(
            self.model, self.optimizer, self.loss_fn,
            strategy=self.strategy, accumulate_steps=accumulate_steps,
            scaler=scaler)
        return self._step

    def fit(self, loader, epochs: int = 1, log_every: int = 0):
        step = getattr(self, "_step", None) or self.prepare()
        history = []
        for _ in range(epochs):
            for i, batch in enumerate(loader):
                loss = step(*batch)
                if log_every and i % log_every == 0:
                    history.append(float(np.asarray(loss._data)))
        return history
