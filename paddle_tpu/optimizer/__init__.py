from . import lr  # noqa: F401
from .algorithms import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, LarsMomentum, Momentum,
    RMSProp, SGD,
)
from .optimizer import Optimizer  # noqa: F401
