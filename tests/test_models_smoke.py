"""Model-zoo smoke tests: forward shapes finite, 3-step loss drop.

Mirrors the reference's model unittests (test_resnet, test_bert, ...):
tiny configs, synthetic data."""
import dataclasses

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as optim


def _ids(vocab, shape, seed=0):
    rng = np.random.default_rng(seed)
    return paddle.to_tensor(rng.integers(0, vocab, shape).astype(np.int32))


def _train_steps(model, make_loss, n=3, lr=1e-3):
    opt = optim.Adam(learning_rate=lr, parameters=model.parameters())
    losses = []
    for _ in range(n):
        loss = make_loss(model)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], f"loss did not drop: {losses}"
    return losses


def test_bert_pretraining_smoke():
    from paddle_tpu.text.models import BERT_TINY, BertForPretraining

    paddle.seed(0)
    m = BertForPretraining(BERT_TINY)
    ids = _ids(BERT_TINY.vocab_size, (2, 32))
    mlm = _ids(BERT_TINY.vocab_size, (2, 32), seed=1)
    nsp = paddle.to_tensor(np.asarray([0, 1], dtype=np.int64))
    _train_steps(m, lambda m: m(ids, masked_lm_labels=mlm,
                                next_sentence_label=nsp))


def test_gpt_smoke():
    from paddle_tpu.text.models import GPT_TINY, GPTForCausalLM

    paddle.seed(0)
    m = GPTForCausalLM(GPT_TINY)
    ids = _ids(GPT_TINY.vocab_size, (2, 32))
    logits = m(ids)
    assert list(logits.shape) == [2, 32, GPT_TINY.vocab_size]
    _train_steps(m, lambda m: m(ids, labels=ids))


def test_ernie_moe_smoke():
    from paddle_tpu.text.models import ERNIE_MOE_TINY, ErnieMoEForPretraining

    paddle.seed(0)
    m = ErnieMoEForPretraining(ERNIE_MOE_TINY)
    ids = _ids(ERNIE_MOE_TINY.vocab_size, (2, 16))
    _train_steps(m, lambda m: m(ids, labels=ids))


def test_vit_smoke():
    from paddle_tpu.vision.models import VisionTransformer

    paddle.seed(0)
    m = VisionTransformer(img_size=32, patch_size=8, embed_dim=64, depth=2,
                          num_heads=4, num_classes=10)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.normal(size=(2, 3, 32, 32)).astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 10, (2,)).astype(np.int64))
    ce = nn.CrossEntropyLoss()
    out = m(x)
    assert list(out.shape) == [2, 10]
    _train_steps(m, lambda m: ce(m(x), y))


@pytest.mark.parametrize("name", ["resnet18", "mobilenet_v2",
                                  "shufflenet_v2_x1_0"])
def test_vision_model_forward(name):
    import paddle_tpu.vision.models as models

    paddle.seed(0)
    fn = getattr(models, name, None)
    if fn is None:
        pytest.skip(f"{name} not exported")
    m = fn(num_classes=10)
    m.eval()
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.normal(size=(2, 3, 64, 64)).astype(np.float32))
    out = m(x)
    assert list(out.shape) == [2, 10]
    assert np.all(np.isfinite(out.numpy()))


def test_llama_generation_cache():
    """KV-cache decode matches full forward (exercises cross-length sdpa)."""
    from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

    cfg = dataclasses.replace(LLAMA_TINY, dtype="float32",
                              num_hidden_layers=2)
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    m.eval()
    ids = _ids(cfg.vocab_size, (1, 8))
    with paddle.no_grad():
        full = m(ids).numpy()
        caches = m.init_cache(1)
        logits_step = None
        for t in range(8):
            logits_step, caches = m(ids[:, t:t + 1], caches=caches)
    np.testing.assert_allclose(logits_step.numpy()[:, 0], full[:, -1],
                               atol=2e-4, rtol=2e-4)


def test_llama_selective_remat_matches_no_remat():
    """remat="selective" (checkpoint policy keeping matmul outputs) must
    be numerically identical to no remat — only memory/recompute differ."""
    import dataclasses

    import numpy as np

    import paddle_tpu
    from paddle_tpu.text.models.llama import LLAMA_TINY, LlamaForCausalLM

    rng = np.random.default_rng(0)
    ids = paddle_tpu.to_tensor(
        rng.integers(0, LLAMA_TINY.vocab_size, (2, 16)).astype(np.int32))

    outs = {}
    for mode in (False, "selective", True):
        paddle_tpu.seed(0)
        cfg = dataclasses.replace(LLAMA_TINY, dtype="float32", remat=mode)
        m = LlamaForCausalLM(cfg)
        loss = m(ids, labels=ids)
        loss.backward()
        g = next(iter(m.parameters())).grad
        outs[mode] = (float(np.asarray(loss._data)),
                      np.asarray(g._data).copy())
    np.testing.assert_allclose(outs["selective"][0], outs[False][0],
                               rtol=1e-6)
    np.testing.assert_allclose(outs["selective"][1], outs[False][1],
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(outs[True][1], outs[False][1],
                               rtol=1e-5, atol=1e-7)
