"""Dynamic-to-static control flow conversion.

Reference: fluid/dygraph/dygraph_to_static (program_translator.py:999,
convert_operators.py) and fluid/layers/control_flow.py cond(:2445) /
while_loop(:1209). Converted functions must compile under jit with
tensor-dependent branches/loops AND match eager execution.
"""
import numpy as np

import paddle_tpu
from paddle_tpu import jit, static


def test_static_nn_cond_eager():
    x = paddle_tpu.to_tensor(3.0)
    out = static.nn.cond(x > 0, lambda: x + 1, lambda: x - 1)
    assert float(out) == 4.0


def test_static_nn_cond_traced():
    @jit.to_static
    def f(x):
        return static.nn.cond(x.sum() > 0, lambda: x * 2, lambda: x * -1)

    r = f(paddle_tpu.to_tensor([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(r._data), [2.0, 4.0])
    r = f(paddle_tpu.to_tensor([-1.0, -2.0]))
    np.testing.assert_allclose(np.asarray(r._data), [1.0, 2.0])


def test_static_nn_while_loop_traced():
    @jit.to_static
    def f(x):
        def cond(i, s):
            return i < 5

        def body(i, s):
            return i + 1, s + x * i.astype("float32")

        i0 = paddle_tpu.to_tensor(0)
        s0 = paddle_tpu.zeros_like(x)
        i, s = static.while_loop(cond, body, [i0, s0])
        return s

    r = f(paddle_tpu.to_tensor([1.0, 2.0]))
    # sum over i=0..4 of x*i = 10*x
    np.testing.assert_allclose(np.asarray(r._data), [10.0, 20.0])


def test_branch_on_tensor_converts():
    """Python `if` over a traced tensor predicate compiles and matches
    eager (the dy2static AST conversion)."""

    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y + 1.0

    eager_pos = f(paddle_tpu.to_tensor([1.0, 2.0]))
    eager_neg = f(paddle_tpu.to_tensor([-3.0, -4.0]))

    sf = jit.to_static(f)
    got_pos = sf(paddle_tpu.to_tensor([1.0, 2.0]))
    got_neg = sf(paddle_tpu.to_tensor([-3.0, -4.0]))
    np.testing.assert_allclose(np.asarray(got_pos._data),
                               np.asarray(eager_pos._data))
    np.testing.assert_allclose(np.asarray(got_neg._data),
                               np.asarray(eager_neg._data))


def test_loop_until_converged_converts():
    """Python `while` over a tensor condition compiles (Newton iteration
    for sqrt, a loop-until-converged shape)."""

    def newton_sqrt(a):
        x = a / 2.0 + 1.0
        err = paddle_tpu.to_tensor(1.0)
        while err > 1e-6:
            nx = 0.5 * (x + a / x)
            err = (nx - x).abs().max()
            x = nx
        return x

    a = paddle_tpu.to_tensor([4.0, 9.0, 2.0])
    eager = newton_sqrt(a)
    np.testing.assert_allclose(np.asarray(eager._data),
                               np.sqrt([4.0, 9.0, 2.0]), rtol=1e-5)

    sf = jit.to_static(newton_sqrt)
    got = sf(a)
    np.testing.assert_allclose(np.asarray(got._data),
                               np.sqrt([4.0, 9.0, 2.0]), rtol=1e-5)


def test_python_predicate_untouched():
    """Concrete (non-tensor) predicates keep plain python behavior after
    conversion."""

    def f(x, flag):
        if flag:
            y = x + 10.0
        else:
            y = x - 10.0
        return y

    sf = jit.to_static(f)
    r = sf(paddle_tpu.to_tensor([1.0]), True)
    np.testing.assert_allclose(np.asarray(r._data), [11.0])
    r = sf(paddle_tpu.to_tensor([1.0]), False)
    np.testing.assert_allclose(np.asarray(r._data), [-9.0])


def test_if_with_return_falls_back():
    """Branches containing `return` stay python (documented limitation) —
    fine with concrete predicates."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x, flag):
        if flag:
            return x * 2.0
        return x * 3.0

    cf = convert_control_flow(f)
    r = cf(paddle_tpu.to_tensor([1.0]), True)
    np.testing.assert_allclose(np.asarray(r._data), [2.0])
    r = cf(paddle_tpu.to_tensor([1.0]), False)
    np.testing.assert_allclose(np.asarray(r._data), [3.0])


def test_switch_case_and_case():
    x = paddle_tpu.to_tensor(2)
    out = static.switch_case(
        x, {1: lambda: paddle_tpu.to_tensor(10.0),
            2: lambda: paddle_tpu.to_tensor(20.0)},
        default=lambda: paddle_tpu.to_tensor(-1.0))
    assert float(out) == 20.0

    out = static.case(
        [(paddle_tpu.to_tensor(False), lambda: paddle_tpu.to_tensor(1.0)),
         (paddle_tpu.to_tensor(True), lambda: paddle_tpu.to_tensor(2.0))],
        default=lambda: paddle_tpu.to_tensor(3.0))
    assert float(out) == 2.0


def test_grad_through_converted_cond():
    def f(x):
        if x.sum() > 0:
            y = x * x
        else:
            y = x * 3.0
        return y.sum()

    sf = jit.to_static(f)
    import jax

    # functional grad through the converted branch
    g = jax.grad(lambda a: sf(paddle_tpu.Tensor(a, stop_gradient=False))._data)(
        np.asarray([1.0, 2.0], dtype=np.float32))
    np.testing.assert_allclose(np.asarray(g), [2.0, 4.0])


def test_for_range_tensor_bound_converts():
    """`for i in range(n)` with a traced tensor bound compiles to a
    lax.while_loop (reference convert_range) and matches eager."""

    def f(x, n):
        s = paddle_tpu.zeros_like(x)
        for i in range(n):
            s = s + x * float(1.0) + i * 0.0
        return s

    # eager with python int bound
    eager = f(paddle_tpu.to_tensor([1.0, 2.0]), 3)
    np.testing.assert_allclose(np.asarray(eager._data), [3.0, 6.0])

    sf = jit.to_static(f)
    got = sf(paddle_tpu.to_tensor([1.0, 2.0]), paddle_tpu.to_tensor(3))
    np.testing.assert_allclose(np.asarray(got._data), [3.0, 6.0])
    got = sf(paddle_tpu.to_tensor([1.0, 2.0]), paddle_tpu.to_tensor(5))
    np.testing.assert_allclose(np.asarray(got._data), [5.0, 10.0])


def test_for_range_start_step_converts():
    def f(x, n):
        acc = paddle_tpu.to_tensor(0.0)
        for i in range(2, n, 2):
            acc = acc + x.sum() * 0 + i
        return acc

    eager = f(paddle_tpu.to_tensor([0.0]), 8)  # i = 2,4,6 -> 12
    assert float(eager) == 12.0
    sf = jit.to_static(f)
    got = sf(paddle_tpu.to_tensor([0.0]), paddle_tpu.to_tensor(8))
    assert float(np.asarray(got._data)) == 12.0
