"""AdaRound: learned up-vs-down weight rounding for PTQ.

Reference: fluid/contrib/slim/quantization/adaround.py:1 (run_adaround —
per-layer alpha optimization with a rectified-sigmoid soft rounding
h(alpha) = clip(sigmoid(alpha)(ZETA-GAMMA)+GAMMA, 0, 1), reconstruction
MSE against the fp layer output, and an annealed regularizer
reg * sum(1 - |2h-1|^beta) that pushes h to {0,1}; 20% warm start).
TPU-native: the whole optimization is ONE jitted Adam loop over alpha
via lax.fori_loop — no per-iteration python, the MXU does the layer
matmuls — and the learned rounding is baked back into the float weight
exactly on the int8 grid, so the existing nearest-rounding
Int8Linear.from_linear reproduces it bit-for-bit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

GAMMA, ZETA = -0.1, 1.1


def _soft_rounding(alpha):
    """Rectified sigmoid h(alpha) in [0, 1] (adaround.py:33)."""
    return jnp.clip(jax.nn.sigmoid(alpha) * (ZETA - GAMMA) + GAMMA,
                    0.0, 1.0)


def adaround_weight(weight, inputs, scale, bits=8, num_iterations=500,
                    reg_param=0.01, beta_range=(20.0, 2.0),
                    warm_start=0.2, lr=1e-2):
    """Learn per-element rounding for one Linear weight.

    weight: [in, out] float array; inputs: [N, in] calibration rows;
    scale: [1, out] (or scalar) symmetric int8 grid step. Returns the
    adarounded weight, whose values sit EXACTLY on the int8 grid.
    """
    w = jnp.asarray(weight, jnp.float32)
    x = jnp.asarray(inputs, jnp.float32)
    s = jnp.asarray(scale, jnp.float32)
    qmax = 2.0 ** (bits - 1) - 1
    wf = w / s
    floor_w = jnp.floor(wf)
    frac = jnp.clip(wf - floor_w, 1e-4, 1.0 - 1e-4)
    # init so h(alpha0) == frac: soft rounding starts at the fp weight
    alpha0 = -jnp.log((ZETA - GAMMA) / (frac - GAMMA) - 1.0)
    orig_out = x @ w
    warm_end = warm_start * num_iterations
    start_beta, end_beta = beta_range

    def qdq(alpha):
        return jnp.clip(floor_w + _soft_rounding(alpha), -qmax, qmax) * s

    def loss_fn(alpha, beta, warm):
        recon = jnp.mean(jnp.sum((x @ qdq(alpha) - orig_out) ** 2, -1))
        h = _soft_rounding(alpha)
        reg = reg_param * jnp.sum(1.0 - jnp.abs(2.0 * h - 1.0) ** beta)
        return recon + jnp.where(warm, 0.0, reg)

    def body(i, carry):
        alpha, m, v = carry
        it = i.astype(jnp.float32)
        warm = it < warm_end
        rel = jnp.clip((it - warm_end)
                       / max(num_iterations - warm_end, 1.0), 0.0, 1.0)
        beta = end_beta + 0.5 * (start_beta - end_beta) * (
            1.0 + jnp.cos(rel * jnp.pi))  # cosine anneal (adaround.py:82)
        g = jax.grad(loss_fn)(alpha, beta, warm)
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1.0 - 0.9 ** (it + 1.0))
        vh = v / (1.0 - 0.999 ** (it + 1.0))
        return (alpha - lr * mh / (jnp.sqrt(vh) + 1e-8), m, v)

    alpha, _, _ = jax.jit(
        lambda a0: jax.lax.fori_loop(
            0, num_iterations, body,
            (a0, jnp.zeros_like(a0), jnp.zeros_like(a0))))(alpha0)
    # hard rounding: h >= 0.5 rounds up (alpha's sign decides)
    rounded = jnp.clip(floor_w + (_soft_rounding(alpha) >= 0.5),
                       -qmax, qmax) * s
    return rounded.astype(jnp.asarray(weight).dtype)


def run_adaround(data_loader, model, max_batches=8, num_iterations=500,
                 reg_param=0.01, beta_range=(20.0, 2.0), warm_start=0.2,
                 lr=1e-2, max_rows=1024):
    """Apply AdaRound to every Linear in ``model`` (reference
    adaround.py:201 run_adaround): collect each layer's calibration
    inputs with forward hooks (capped at ``max_rows`` rows per layer so
    peak host memory stays bounded), learn its rounding, bake the
    result into the float weight on the int8 grid, and PIN the grid on
    the layer (``_adaround_scale``) so Int8Linear.from_linear converts
    on the same scale the rounding was learned on. Conv2D layers are
    not adarounded (the reference covers them; here they keep nearest
    rounding) — a warning is emitted when the model has any."""
    from ..layer.common import Linear
    from ..layer.conv import Conv2D
    from ...tensor import Tensor
    from .qat import calibration_pass

    captured = {}
    targets = [(n, l) for n, l
               in model.named_sublayers(include_self=True)
               if type(l) is Linear]
    if any(type(l) is Conv2D
           for _, l in model.named_sublayers(include_self=True)):
        import warnings
        warnings.warn(
            "run_adaround: Conv2D layers keep nearest rounding "
            "(AdaRound here optimizes Linear weights only)",
            stacklevel=2)

    def observe(name):
        def hook(layer, inputs, output=None):
            got = sum(r.shape[0] for r in captured.get(name, ()))
            if got >= max_rows:
                return
            xin = inputs[0] if isinstance(inputs, (tuple, list)) \
                else inputs
            raw = xin._data if isinstance(xin, Tensor) else jnp.asarray(xin)
            rows = np.asarray(raw, np.float32).reshape(-1, raw.shape[-1])
            captured.setdefault(name, []).append(rows[:max_rows - got])
        return hook

    calibration_pass(model, data_loader,
                     [(layer, observe(name)) for name, layer in targets],
                     max_batches=max_batches)

    from . import quantize_int8
    for name, layer in targets:
        rows = captured.pop(name, None)
        if not rows:
            continue
        x = np.concatenate(rows, axis=0)
        _, s = quantize_int8(layer.weight._data, axis=0)  # [1, out]
        s = s._data if hasattr(s, "_data") else s
        layer.weight._data = adaround_weight(
            layer.weight._data, x, s,
            num_iterations=num_iterations, reg_param=reg_param,
            beta_range=beta_range, warm_start=warm_start, lr=lr)
        layer._adaround_scale = np.asarray(s)  # pin the learned grid
    return model
