"""Rule registry for tpu_lint.

Rules come in two kinds:

* ``program`` — run on a :class:`~paddle_tpu.analysis.audit.ProgramView`
  (traced jaxpr + lowered StableHLO + origin metadata) and yield
  :class:`~paddle_tpu.analysis.findings.Finding`s;
* ``ast`` — run on a :class:`~paddle_tpu.analysis.rules_ast.SourceFile`
  (parsed python source) during the self-lint.

Registering a rule is one decorator::

    @rule("my-rule", kind="program", severity="medium",
          title="what it catches")
    def _my_rule(view):
        yield Finding("my-rule", "medium", "...")

The decorated function may yield findings with any severity; the
registered ``severity`` is the rule's *default/documented* level (shown
in the README table and the CLI rule listing). Rule ids may be shared
across kinds (``dtype-promotion`` has both a program and an AST facet).
"""
from __future__ import annotations

from dataclasses import dataclass

from .findings import SEVERITIES


@dataclass(frozen=True)
class Rule:
    id: str
    kind: str            # "program" | "ast"
    severity: str        # documented default severity
    title: str
    fn: object

    def run(self, target):
        return self.fn(target)


_RULES: list = []


def rule(rule_id: str, *, kind: str, severity: str, title: str):
    if kind not in ("program", "ast"):
        raise ValueError(f"unknown rule kind {kind!r}")
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r}")

    def deco(fn):
        _RULES.append(Rule(rule_id, kind, severity, title, fn))
        return fn

    return deco


def iter_rules(kind=None, ids=None):
    for r in _RULES:
        if kind is not None and r.kind != kind:
            continue
        if ids is not None and r.id not in ids:
            continue
        yield r


def rules_table():
    """[(id, kind, severity, title)] for docs/CLI listing."""
    return [(r.id, r.kind, r.severity, r.title) for r in _RULES]
