"""paddle.compat — py2/3-era text/number helpers some legacy scripts call.

Reference: python/paddle/compat.py:25 (six-based to_text/to_bytes/round/
floor_division/get_exception_message). Python-3-only here; the container
recursion semantics (inplace for list/set, new dict) match the reference.
"""
from __future__ import annotations

import math

__all__ = []


def _map_container(obj, fn, inplace):
    if isinstance(obj, list):
        if inplace:
            obj[:] = [fn(x) for x in obj]
            return obj
        return [fn(x) for x in obj]
    if isinstance(obj, set):
        new = {fn(x) for x in obj}
        if inplace:
            obj.clear()
            obj.update(new)
            return obj
        return new
    if isinstance(obj, dict):
        return {fn(k): fn(v) for k, v in obj.items()}
    return None


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes → str, recursively through list/set/dict; others untouched."""
    if obj is None:
        return obj
    mapped = _map_container(obj, lambda x: to_text(x, encoding), inplace)
    if mapped is not None:
        return mapped
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    return obj


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str → bytes, recursively through list/set/dict; others untouched."""
    if obj is None:
        return obj
    mapped = _map_container(obj, lambda x: to_bytes(x, encoding), inplace)
    if mapped is not None:
        return mapped
    if isinstance(obj, str):
        return obj.encode(encoding)
    return obj


def round(x, d=0):
    """Python-2-style round (half away from zero), reference compat.py:206."""
    if x > 0.0:
        p = 10 ** d
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0.0:
        p = 10 ** d
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return math.copysign(0.0, x)


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    assert exc is not None
    return str(exc)
