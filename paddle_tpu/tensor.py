"""The paddle_tpu Tensor.

Reference surface: python/paddle/fluid/dygraph/varbase_patch_methods.py and
python/paddle/tensor/tensor.py — a Tensor with ``stop_gradient`` (note:
paddle's default is True; Parameters default to False), ``.grad``,
``backward()``, ``numpy()``, and ~200 method aliases of the functional ops.

Implementation: a thin wrapper over a jax array. Every op is a pure jnp
function routed through :func:`apply` which (a) unwraps inputs, (b) runs the
jnp computation (eagerly on device, or as a tracer under jit), and (c) when
the eager tape is live, records a VJP node. Tensor is registered as a jax
pytree node, so Tensors pass transparently through jax.jit / shard_map /
grad when used functionally.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .autograd import tape
from .framework import dispatch_cache as _dcache
from .observability.compile_attr import compile_scope as _compile_scope
from .framework import dtype as dtype_mod


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_index",
                 "name", "persistable", "_grad_hooks", "_token",
                 "__weakref__")

    def __init__(self, data, dtype=None, stop_gradient: bool = True,
                 name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            dt = dtype_mod.convert_dtype(dtype)
            arr = np.asarray(data)
            if dt is None and arr.dtype == np.float64:
                dt = dtype_mod.get_default_dtype()
            data = jnp.asarray(arr, dtype=dt)
        elif dtype is not None:
            dt = dtype_mod.convert_dtype(dtype)
            if dt is not None and data.dtype != dt:
                data = data.astype(dt)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_index = 0
        self.name = name
        self.persistable = False
        self._grad_hooks = None
        # lazily-assigned monotonic id used by the static-graph jit cache:
        # unlike id(), a token is never reused after the Tensor dies, so a
        # cached "not jittable" verdict can't be resurrected by id reuse
        self._token = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    ndimension = dim = lambda self: self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def place(self):
        try:
            devs = getattr(self._data, "devices", None)
            return next(iter(devs())) if callable(devs) else "tpu"
        except Exception:
            return "traced"

    @property
    def T(self):
        from . import tensor_ops as ops
        return ops.t(self)

    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        return self._data.item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def numel(self):
        return self.size

    def element_size(self):
        return self._data.dtype.itemsize

    def is_leaf(self):
        return self._node is None

    def detach(self):
        return Tensor(self._data, stop_gradient=True)

    def clone(self):
        from . import tensor_ops as ops
        return ops.clone(self)

    def astype(self, dtype):
        from . import tensor_ops as ops
        return ops.cast(self, dtype)

    cast = astype

    def cpu(self):
        return Tensor(jax.device_get(self._data), stop_gradient=self.stop_gradient)

    def cuda(self, *a, **k):  # compat no-op: data already on accelerator
        return self

    def to(self, *args, **kwargs):
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu") or ":" in str(a):
                continue
            dtype = a
        return self.astype(dtype) if dtype is not None else self

    def pin_memory(self):
        return self

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        tape.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self.grad = None

    def gradient(self):
        """The accumulated gradient as a numpy array, or None
        (reference varbase_patch_methods.gradient)."""
        if self.grad is None:
            return None
        import numpy as _np

        return _np.asarray(self.grad._data)

    def get_value(self):
        """The tensor's value as a detached Tensor (reference
        varbase_patch_methods get_value — paired with set_value for
        checkpoint flows)."""
        return Tensor(self._data)

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {tuple(value.shape)} vs "
                f"{tuple(self._data.shape)} (the reference rejects "
                f"mismatched shapes too)")
        self._data = value
        self._node = None

    def clear_gradient(self):
        self.grad = None

    def register_hook(self, hook):
        """Register a backward hook ``hook(grad) -> Tensor | None``.

        Called when this tensor's gradient is computed during ``backward()``;
        a non-None return replaces the gradient that continues to propagate
        (and, for leaves, what accumulates into ``.grad``). Returns a handle
        with ``.remove()``. Reference:
        fluid/dygraph/varbase_patch_methods.py:353 (register_hook on the
        C++ GradNode); here the tape applies hooks to the accumulated
        cotangent of this tensor."""
        if self.stop_gradient:
            raise RuntimeError(
                "Cannot register hook on a Tensor with stop_gradient=True")
        if self._grad_hooks is None:
            self._grad_hooks = {}
        # conservative: registering a hook may change what a cached
        # backward half should observe — drop compiled entries
        _dcache.invalidate()
        return tape.HookHandle(self._grad_hooks, hook)

    # -- display ------------------------------------------------------------
    def __repr__(self):
        sg = self.stop_gradient
        if not hasattr(self._data, "shape"):
            # placeholder payload from a jax-internal tree unflatten
            return f"Tensor(<opaque {type(self._data).__name__}>)"
        try:
            body = np.array2string(np.asarray(self._data), precision=8,
                                   separator=", ", prefix="       ")
        except Exception:
            body = f"<traced {self._data}>"
        return (f"Tensor(shape={self.shape}, dtype={self._data.dtype}, "
                f"stop_gradient={sg},\n       {body})")

    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def _scalar(self):
        """paddle converts any size-1 tensor to a python scalar (shape
        [1] or [1,1] included); jax only converts rank-0 — squeeze."""
        d = self._data
        if getattr(d, "ndim", 0) and getattr(d, "size", 1) == 1:
            d = d.reshape(())
        return d

    def __bool__(self):
        return bool(self._scalar())

    def __int__(self):
        return int(self._scalar())

    def __float__(self):
        return float(self._scalar())

    def __index__(self):
        return int(self._scalar())

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return format(str(self), spec)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __hash__(self):
        return id(self)

    # arithmetic dunders are attached in tensor_ops/_bind.py


def _tensor_flatten(t: Tensor):
    return (t._data,), t.stop_gradient


def _tensor_unflatten(aux, children):
    c = children[0]
    if isinstance(c, (jax.Array, jax.core.Tracer, np.ndarray, np.generic,
                      bool, int, float, complex)):
        return Tensor(c, stop_gradient=aux)
    # jax internally unflattens argument trees with non-array leaves
    # (sharding specs, sentinel objects) while resolving pjit
    # in/out_shardings; those must pass through untouched — coercing
    # them via jnp.asarray raises and breaks any jit whose argument
    # tree contains a Tensor node alongside explicit shardings.
    t = object.__new__(Tensor)
    t._data = c
    t.stop_gradient = aux
    t.grad = None
    t._node = None
    t._out_index = 0
    t.name = None
    t.persistable = False
    t._grad_hooks = None
    t._token = None
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


class Parameter(Tensor):
    """Trainable tensor (reference: fluid.framework.Parameter/EagerParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed",
                 "is_mp", "split_axis", "pspec", "is_sparse_table")

    def __init__(self, data, dtype=None, trainable: bool = True,
                 name: Optional[str] = None):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.is_mp = False
        self.split_axis = None
        self.pspec = None  # jax PartitionSpec for the distributed path
        self.is_sparse_table = False  # lazy-row optimizer semantics marker

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._data,), (p.stop_gradient,)),
    lambda aux, ch: Parameter(ch[0], trainable=not aux[0]),
)


# Static-graph recorder (paddle.static emulation): when set, every op that
# flows through apply()/nondiff() is appended to the active Program so
# Executor.run can replay it with fed placeholder values.
_op_recorder = None


def set_op_recorder(recorder):
    global _op_recorder
    prev = _op_recorder
    _op_recorder = recorder
    return prev


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_out(val, stop_gradient):
    return Tensor(val, stop_gradient=stop_gradient)


def _cached_dispatch(fn, args, raw, kwargs, diff_idx):
    """Signature-keyed fast path (framework.dispatch_cache): steady-state
    eager ops run as two compiled halves instead of re-tracing jax.vjp.
    Returns the wrapped result, or None when the caller must fall back."""
    hit = _dcache.dispatch(fn, raw, kwargs, diff_idx)
    if hit is None:
        return None
    out, pullback, entry = hit
    multi = isinstance(out, (tuple, list))
    if not diff_idx:
        if multi:
            return tuple(_wrap_out(o, True) for o in out)
        return _wrap_out(out, True)
    outs = tuple(out) if multi else (out,)

    def vjp_fn(out_cts):
        cts = tuple(
            jnp.zeros_like(o) if ct is None else ct
            for o, ct in zip(outs, out_cts)
        )
        return entry.backward(pullback, cts if multi else cts[0])

    wrapped = tuple(_wrap_out(o, False) for o in outs)
    tape.record(vjp_fn, [args[i] for i in diff_idx], wrapped)
    return wrapped if multi else wrapped[0]


def apply(fn: Callable, *args, n_outputs: Any = 1, **kwargs):
    """Run primitive ``fn`` (a pure jnp function) on mixed Tensor/array args.

    Differentiates w.r.t. positional Tensor args whose stop_gradient is
    False; kwargs are always non-differentiable constants. Returns Tensor(s).
    """
    taping = tape.grad_enabled()
    diff_idx = []
    if taping:
        for i, a in enumerate(args):
            if isinstance(a, Tensor) and not a.stop_gradient:
                diff_idx.append(i)
    raw = [_unwrap(a) for a in args]

    if _dcache.enabled() and _op_recorder is None:
        res = _cached_dispatch(fn, args, raw, kwargs, tuple(diff_idx))
        if res is not None:
            return res

    if not diff_idx:
        # compile attribution: a cold jnp primitive compiling under this
        # op lands in paddle_xla_compiles_total{origin="eager:<op>"}
        with _compile_scope(f"eager:{getattr(fn, '__name__', 'op')}"):
            out = fn(*raw, **kwargs)
        if isinstance(out, (tuple, list)):
            res = tuple(_wrap_out(o, True) for o in out)
        else:
            res = _wrap_out(out, True)
        if _op_recorder is not None:
            _op_recorder(fn, args, kwargs, res)
        return res

    parents = [args[i] for i in diff_idx]

    def closed(*diff_vals):
        vals = list(raw)
        for i, v in zip(diff_idx, diff_vals):
            vals[i] = v
        return fn(*vals, **kwargs)

    with _compile_scope(f"eager:{getattr(fn, '__name__', 'op')}"):
        out, vjp = jax.vjp(closed, *(raw[i] for i in diff_idx))
    multi = isinstance(out, (tuple, list))
    outs = tuple(out) if multi else (out,)

    def vjp_fn(out_cts):
        cts = tuple(
            jnp.zeros_like(o) if ct is None else ct
            for o, ct in zip(outs, out_cts)
        )
        return vjp(cts if multi else cts[0])

    wrapped = tuple(_wrap_out(o, False) for o in outs)
    tape.record(vjp_fn, parents, wrapped)
    res = wrapped if multi else wrapped[0]
    if _op_recorder is not None:
        _op_recorder(fn, args, kwargs, res)
    return res


def nondiff(fn: Callable, *args, **kwargs):
    """Apply a non-differentiable op (argmax, comparisons, ...)."""
    raw = [_unwrap(a) for a in args]
    if _dcache.enabled() and _op_recorder is None:
        res = _cached_dispatch(fn, args, raw, kwargs, ())
        if res is not None:
            return res
    with _compile_scope(f"eager:{getattr(fn, '__name__', 'op')}"):
        out = fn(*raw, **kwargs)
    if isinstance(out, (tuple, list)):
        res = tuple(_wrap_out(o, True) for o in out)
    else:
        res = _wrap_out(out, True)
    if _op_recorder is not None:
        _op_recorder(fn, args, kwargs, res)
    return res


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py:to_tensor)."""
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, stop_gradient=stop_gradient)
        return t
    if isinstance(data, (bool, int)) and dtype is None:
        # match paddle: python ints -> int64 (jax x64-off folds to int32)
        data = np.asarray(data)
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def __getattr__(name):
    """Module-level fallback (PEP 562): the reference's ``paddle.tensor``
    package re-exports every tensor op (``paddle.tensor.triu`` etc.);
    here the ops live in tensor_ops — forward unknown attributes there
    so both spellings work while this module keeps owning the Tensor
    class."""
    import sys
    import types

    if name.startswith("_"):
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    pkg = __name__.rsplit(".", 1)[0]
    # stat precedes math: stat.mean/std/var win (the documented
    # tensor_ops precedence), matching paddle_tpu.tensor_ops resolution
    for sub in ("stat", "creation", "manipulation", "logic", "search",
                "math", "linalg", "random", "einsum", "extras"):
        # sys.modules only: all tensor_ops submodules are loaded with the
        # package; a missing entry means we're mid-package-init and must
        # not trigger circular imports for a speculative probe
        mod = sys.modules.get(pkg + ".tensor_ops." + sub)
        if mod is None or not hasattr(mod, name):
            continue
        value = getattr(mod, name)
        if isinstance(value, types.ModuleType):
            continue  # don't leak jnp/np module imports
        globals()[name] = value  # cache: next access skips the scan
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
