#!/usr/bin/env python
"""Eager dispatch fast-path microbench: MLP train step, cached vs uncached.

Measures ms/step of a pure-eager 2-layer MLP train loop (forward,
cross-entropy, backward, Adam step, clear_grad) with the signature-keyed
dispatch cache on and off, verifies the loss trajectories are
bit-identical, and reports the steady-state retrace count. Emits one
JSON ledger line (same convention as tools/bench_conv.py).

Usage: JAX_PLATFORMS=cpu python tools/bench_eager.py [--steps N]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    # past both engage thresholds (32 sightings / 32 optimizer steps):
    # the measured phase is steady state; the loss parity check still
    # covers the whole run including the engage boundary
    ap.add_argument("--warmup", type=int, default=40)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    import numpy as np

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.framework import dispatch_cache

    rng = np.random.default_rng(0)
    x_np = rng.standard_normal((args.batch, args.hidden)).astype(np.float32)
    y_np = rng.integers(0, 10, (args.batch,)).astype(np.int64)

    def build():
        paddle.seed(0)
        net = paddle.nn.Sequential(
            paddle.nn.Linear(args.hidden, args.hidden), paddle.nn.ReLU(),
            paddle.nn.Linear(args.hidden, 10))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        return net, opt

    x = paddle.to_tensor(x_np)
    y = paddle.to_tensor(y_np)

    def run(enabled):
        dispatch_cache.set_enabled(enabled)
        net, opt = build()

        def step():
            loss = paddle.nn.functional.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        losses = []
        for _ in range(args.warmup):
            losses.append(float(step().numpy()))
        before = dispatch_cache.dispatch_stats()
        t0 = time.perf_counter()
        for _ in range(args.steps):
            losses.append(float(step().numpy()))
        ms = (time.perf_counter() - t0) / args.steps * 1e3
        after = dispatch_cache.dispatch_stats()
        retraces = sum(after[k] - before[k]
                       for k in ("misses", "compiles", "bypasses"))
        dispatch_cache.set_enabled(True)
        return ms, losses, retraces

    ms_off, losses_off, _ = run(False)
    ms_on, losses_on, retraces = run(True)

    bit_identical = losses_off == losses_on
    speedup = ms_off / ms_on if ms_on else float("inf")
    ok = bit_identical and speedup >= 5.0 and retraces == 0

    print(json.dumps({
        "bench": "eager_mlp_train_step",
        "backend": jax.default_backend(),
        "batch": args.batch, "hidden": args.hidden, "steps": args.steps,
        "eager_ms_per_step_uncached": round(ms_off, 3),
        "eager_ms_per_step_cached": round(ms_on, 3),
        "speedup": round(speedup, 2),
        "bit_identical_losses": bit_identical,
        "steady_state_retraces": retraces,
        "first_losses": [round(v, 6) for v in losses_on[:3]],
        "cache": dispatch_cache.dispatch_stats(),
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
