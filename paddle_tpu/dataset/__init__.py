"""paddle.dataset compatibility namespace (reference:
python/paddle/dataset/__init__.py)."""
from . import common  # noqa: F401
